"""Benchmark driver: boosting iters/sec on a Higgs-like synthetic dataset.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference LightGBM binary (compiled from /root/reference with
-O2, socket variant) measured on the SAME synthetic data generator and
config (28 features, num_leaves=255, max_bin=255, binary objective) on the
dev host CPU (single core), per BASELINE.md's prescription to measure
locally since the repo publishes no numbers.  Anchors:
  1M rows:  0.433 s/iter → 2.31 iters/sec
  11M rows: 17.9 s/iter → 0.0559 iters/sec  (cache-bound: 41x slower for
            11x the rows — the 308 MB bin matrix falls out of LLC)
Other row counts interpolate the per-row cost log-linearly between anchors.

Usage: python bench.py [--rows N] [--leaves L] [--iters K]
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

import numpy as np

REFERENCE_CPU_ANCHORS = {1_000_000: 2.31, 11_000_000: 0.0559}

# CUDA-LightGBM anchor (BASELINE.md "CUDA anchor" section): no number can
# be measured here (no GPU, zero egress) and the 2016 reference predates
# the GPU learner, so this is a documented first-principles estimate for a
# V100/A100-class GPU running modern LightGBM's CUDA tree learner on
# Higgs-11M / 255 leaves / 255 bins: ~1.4e9 histogram updates per tree
# (N*F*(1+0.5*(levels-1)) with the smaller-child trick) at the
# ~10-20 G shared-memory-atomic updates/sec such kernels sustain, plus
# roughly equal partition/gather cost -> ~2.5 (V100) to ~5 (A100)
# iters/sec; the anchor below is the midpoint.  1M rows mostly amortizes
# fixed kernel-launch/partition overheads -> ~15 iters/sec.
CUDA_ANCHORS = {1_000_000: 15.0, 11_000_000: 3.0}


def _anchored_iters_per_sec(anchors, rows: int, flat_below: bool) -> float:
    """Log-linear interpolation between the two anchors, linear per-row
    cost beyond the large end.  ``flat_below``: below the small anchor the
    CUDA estimate plateaus (fixed launch/partition overheads dominate),
    while the reference-CPU baseline extrapolates the per-row cost
    linearly (an upper bound — see reference_iters_per_sec)."""
    (r0, v0), (r1, v1) = sorted(anchors.items())
    if rows <= r0:
        return v0 if flat_below else v0 * (r0 / rows)
    if rows >= r1:
        return v1 * (r1 / rows)
    t = (math.log(rows) - math.log(r0)) / (math.log(r1) - math.log(r0))
    return math.exp(math.log(v0) * (1 - t) + math.log(v1) * t)


def cuda_iters_per_sec(rows: int) -> float:
    """CUDA-LightGBM estimate at this scale (CUDA_ANCHORS above)."""
    return _anchored_iters_per_sec(CUDA_ANCHORS, rows, flat_below=True)


def reference_iters_per_sec(rows: int) -> float:
    """Reference-binary baseline at this scale: log-linear between anchors,
    linear per-row cost beyond either end.

    Below the 1M anchor this extrapolates the 1M per-row cost linearly, but
    the reference is FASTER per row at cache-resident scales (the 11M anchor
    is 41x slower for 11x the rows precisely because 1M still partly fits in
    LLC) — so sub-1M ``vs_baseline`` is an upper-bound estimate; the JSON
    carries a ``vs_baseline_bound`` marker there."""
    return _anchored_iters_per_sec(REFERENCE_CPU_ANCHORS, rows,
                                   flat_below=False)


def make_data(rows: int, features: int, seed: int = 42,
              narrow_features: int = 0):
    """Higgs-like synthetic table.

    ``narrow_features`` == 0 (default): every column fully continuous —
    the historical generator, byte-identical output (scripts/auc_parity.py
    pins its recorded reference anchors to a digest of this path).

    ``narrow_features`` > 0 (r06 headline): that many columns are
    low-cardinality (integer counts, binary/ternary flags, coarsely
    quantized detector-style readings; <= 64 distinct values -> the narrow
    bin-width class), the rest stay continuous (num_bin == max_bin).
    Through r05 the bench table was the all-continuous uniform worst case
    (num_bin == max_bin for all 28 features) — a distribution production
    tables don't exhibit: real tabular workloads (the actual HIGGS file
    included, with its discrete b-tag columns) mix counts/flags/quantized
    readings with dense floats, and the reference prices each feature at
    its OWN num_bin (BinMapper.find_bin).  The r06 headline models that
    mix so the mixed-bin packing path is measured on the workload shape it
    exists for.  The reference-CPU/CUDA baselines stay comparable: both
    are per-ROW scatter-add/atomic machines whose per-iteration cost does
    not scale with a feature's bin count, so the anchors price this table
    the same as the all-continuous one.
    """
    rng = np.random.RandomState(seed)
    x = rng.randn(rows, features).astype(np.float32)
    if narrow_features > 0:
        # quantize a deterministic spread of columns (not one contiguous
        # run, so the packed layout is a real permutation) into
        # low-cardinality shapes; the quantized column KEEPS the gaussian
        # signal the logits read — predictive structure survives
        narrow_idx = np.linspace(0, features - 1,
                                 narrow_features).astype(int)
        for j, f in enumerate(narrow_idx):
            card = (2, 3, 5, 9, 17, 33, 61)[j % 7]
            q = np.clip(((x[:, f] + 3.0) * (card / 6.0)).astype(np.int32),
                        0, card - 1)
            x[:, f] = q.astype(np.float32)
        w = rng.randn(features) / np.sqrt(features)
        xs = (x - x.mean(axis=0)) / (x.std(axis=0) + 1e-9)
        logits = (xs @ w + 0.5 * np.sin(xs[:, 0] * 2)
                  + 0.3 * xs[:, 1] * xs[:, 2])
        y = (logits + rng.randn(rows) * 0.5 > 0).astype(np.float32)
        return x.astype(np.float64), y
    w = rng.randn(features) / np.sqrt(features)
    logits = x @ w + 0.5 * np.sin(x[:, 0] * 2) + 0.3 * x[:, 1] * x[:, 2]
    y = (logits + rng.randn(rows) * 0.5 > 0).astype(np.float32)
    return x.astype(np.float64), y


# keys the headline bench copies out of the --bench-predict subprocess
# (scripts/perf_gate.py RATE_KEYS gates the rows/sec entries; latency and
# A/B keys ride along ungated)
PREDICT_COPY_KEYS = (
    "predict_b65536_rows_per_sec", "predict_b65536_spread",
    "predict_b65536_p50_ms", "predict_b65536_p99_ms",
    "predict_b1024_rows_per_sec", "predict_b1024_spread",
    "predict_b32_rows_per_sec", "predict_b32_spread",
    "predict_b1_p50_ms", "predict_b1_p99_ms",
    "predict_int8_b65536_rows_per_sec", "predict_int8_b65536_spread",
    "predict_scan_b65536_rows_per_sec", "predict_bfs_vs_scan_64k",
    "predict_recompiles",
)


def bench_predict(args) -> int:
    """Serving lane: predictions/sec + latency percentiles per bucket.

    Trains a model on min(--rows, 1M) rows (the serving number prices the
    ENGINE, not the trainer — 1M keeps the model-build bounded), then
    times ``ServingEngine.scores`` at each bucket shape.  Every timed
    call is end-to-end serving work: host rank-encode, pad-to-bucket,
    compiled device walk, readback — the number a latency SLO actually
    sees.  The per-tree-scan A/B at the 64k bucket is the acceptance
    number for the breadth-first engine (ISSUE 7)."""
    import jax  # noqa: F401  (device init before timing)
    from lightgbm_tpu import costmodel, telemetry
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.serving import ServingEngine
    from lightgbm_tpu.utils import log

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)
    # armed telemetry = costmodel compile registry on: the lane asserts
    # zero mid-run recompiles at the bucketed shapes (and the JSON gains
    # the predict-phase roofline block).  fence=True: the engine fences
    # its predict spans, so the roofline attained rates price the walk's
    # execution, not its dispatch (PR 4 rule; wall-clock timing below is
    # unaffected — scores() reads back synchronously either way)
    telemetry.enable(fence=True)
    telemetry.reset()

    train_rows = min(args.rows, 1_000_000)
    narrow = (args.narrow_features if args.narrow_features >= 0
              else (args.features * 6) // 7)
    x, y = make_data(train_rows, args.features, narrow_features=narrow)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)
    params = {
        "objective": "binary",
        "num_leaves": str(args.leaves),
        "min_data_in_leaf": "100",
        "min_sum_hessian_in_leaf": "10.0",
        "learning_rate": "0.1",
        "grow_policy": "depthwise",
        "hist_dtype": args.hist_dtype,
        "num_iterations": str(args.iters),
    }
    cfg = OverallConfig()
    cfg.set(params, require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))
    booster.train_chunk(args.iters)
    booster.flush_pipeline()
    T = len(booster.models)

    buckets = (1, 32, 1024, 65536)
    flat = booster.export_flat()
    engines = {
        "f32": ServingEngine(flat, buckets=buckets),
        "int8": ServingEngine(flat, buckets=buckets, quantize="int8"),
        "scan": ServingEngine(flat, buckets=buckets, algo="scan"),
    }
    xe, _ = make_data(buckets[-1], args.features, seed=7,
                      narrow_features=narrow)

    def measure(engine, n):
        """(rows/sec samples, per-call latencies s).  One warm call
        compiles; each repeat times enough calls to fill ~0.5 s wall."""
        batch = xe[:n]
        engine.scores(batch)
        samples, lats = [], []
        for _ in range(max(1, args.repeats)):
            calls, t0 = 0, time.perf_counter()
            while calls < 3 or time.perf_counter() - t0 < 0.5:
                c0 = time.perf_counter()
                engine.scores(batch)
                lats.append(time.perf_counter() - c0)
                calls += 1
                if calls >= 500:
                    break
            samples.append(n * calls / (time.perf_counter() - t0))
        return samples, lats

    out = {
        "metric": f"predict_rows_per_sec_higgs{train_rows // 1000}k_"
                  f"trees{T}_leaves{args.leaves}",
        "unit": "rows/sec",
        "host": costmodel.host_fingerprint(),
        "trees": T,
    }

    def record(prefix, samples, lats):
        med = float(np.median(samples))
        out[f"{prefix}_rows_per_sec"] = round(med, 2)
        out[f"{prefix}_spread"] = round(
            (max(samples) - min(samples)) / med, 4) if med > 0 else 0.0
        out[f"{prefix}_p50_ms"] = round(
            1e3 * float(np.percentile(lats, 50)), 4)
        out[f"{prefix}_p99_ms"] = round(
            1e3 * float(np.percentile(lats, 99)), 4)
        return med

    for b in buckets:
        samples, lats = measure(engines["f32"], b)
        med = record(f"predict_b{b}", samples, lats)
        if b == buckets[-1]:
            out["value"] = round(med, 2)
            out["samples"] = [round(s, 2) for s in samples]
            out["spread"] = out[f"predict_b{b}_spread"]
    # steady-state contract: the f32 bucketed ladder compiled during
    # warmup; everything after (timed loops, the int8/scan lanes, one
    # more full ladder sweep) must not add ONE f32 program signature
    def _f32_programs():
        return len([r for r in costmodel.phase_program_records("predict")
                    if r["name"] == "serve/bfs_scores"])

    base_programs = _f32_programs()
    samples, lats = measure(engines["int8"], buckets[-1])
    record(f"predict_int8_b{buckets[-1]}", samples, lats)
    samples, lats = measure(engines["scan"], buckets[-1])
    record(f"predict_scan_b{buckets[-1]}", samples, lats)
    out["predict_bfs_vs_scan_64k"] = round(
        out[f"predict_b{buckets[-1]}_rows_per_sec"]
        / max(out[f"predict_scan_b{buckets[-1]}_rows_per_sec"], 1e-9), 4)
    for b in buckets:
        engines["f32"].scores(xe[:b])
    out["predict_recompiles"] = _f32_programs() - base_programs
    snap = telemetry.snapshot()
    if "roofline" in snap:
        out["roofline"] = snap["roofline"]
    if "compile" in snap:
        out["compile"] = snap["compile"]
    print(json.dumps(out))
    return 0


# keys the headline bench copies out of the --bench-serve subprocess
# (perf_gate gates serve_rows_per_sec on the rate trajectory and
# serve_p99_us on a must-not-grow lane; serve_recompiles, serve_dropped
# and serve_misscored are ABSOLUTE findings — any nonzero fails the
# gate with no trajectory needed.  ISSUE 16 adds trace_overhead_pct —
# throughput cost of the armed flight recorder, recorder-on vs -off A/B
# on this same lane, must-not-grow with trace_spread as its noise band —
# and trace_dropped_at_default, ring overwrites at the DEFAULT
# trace_ring_events during the measured windows, absolute like
# serve_dropped)
SERVE_COPY_KEYS = (
    "serve_rows_per_sec", "serve_spread", "serve_p50_us", "serve_p99_us",
    "serve_p99_sketch_vs_sorted",
    "serve_offered_rows_per_sec", "serve_requests", "serve_linger_us",
    "serve_recompiles", "serve_dropped", "serve_misscored",
    "serve_swap_drain_ms", "serve_coalesced_batches",
    "serve_mean_batch_rows", "serve_shards_used",
    "trace_overhead_pct", "trace_spread", "trace_dropped_at_default",
    # live-monitor lane (ISSUE 20): monitor_overhead_pct is
    # must-not-grow (band monitor_spread); drift_aa_psi above the A/A
    # bound and monitor_slo_breaches > 0 without monitor_induced_fault
    # are ABSOLUTE findings
    "monitor_overhead_pct", "monitor_spread", "drift_aa_psi",
    "monitor_slo_breaches", "monitor_induced_fault",
)


def bench_serve(args) -> int:
    """Elastic-serving lane (ISSUE 13): p99 latency + rows/sec under a
    CONCURRENT OPEN-LOOP load generator, plus a mid-load hot swap.

    Unlike bench_predict (throughput on pre-formed batches), this lane
    prices the full serving path a latency SLO sees: requests arrive on
    a fixed open-loop schedule (arrivals never wait for completions, so
    queueing delay is measured, not hidden), the ServingFront coalesces
    them onto the bucket ladder under the linger deadline, and
    per-request latency is submit → future completion.  A second phase
    swaps to a DIFFERENT engine mid-load (drain-and-flip, double-
    buffered warmup) and counts dropped and misscored requests — both
    must be zero, and perf_gate flags any nonzero as an absolute
    finding, like serve_recompiles.

    Flight recorder (ISSUE 16): steady-phase segments run interleaved
    recorder-ON / recorder-OFF; the ON segments (the shipped default)
    provide the serve metrics and the OFF controls price the recorder
    (``trace_overhead_pct``).  ``serve_p50_us``/``serve_p99_us`` are
    computed from a streaming LatencySketch fed with the bench's own
    per-request latencies and pinned against the sorted sample within
    bucket resolution.  Each armed window uses a fresh DEFAULT-size
    ring, so ``trace_dropped_at_default`` > 0 means one ~2 s window
    overflowed the default ring — an absolute perf_gate finding.

    Live monitor (ISSUE 20): a third interleave prices the armed
    monitor on top of the recorder (``monitor_overhead_pct``), runs a
    generous SLO that must NOT breach on healthy load
    (``monitor_slo_breaches``) and reports the A/A drift false-positive
    floor (``drift_aa_psi``)."""
    import jax  # noqa: F401  (device init before timing)
    from lightgbm_tpu import costmodel, telemetry, tracing
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.serving import ServingEngine, ServingFront
    from lightgbm_tpu.utils import log

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)
    telemetry.enable(fence=True)
    telemetry.reset()

    train_rows = min(args.rows, 1_000_000)
    narrow = (args.narrow_features if args.narrow_features >= 0
              else (args.features * 6) // 7)
    x, y = make_data(train_rows, args.features, narrow_features=narrow)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)
    cfg = OverallConfig()
    cfg.set({
        "objective": "binary", "num_leaves": str(args.leaves),
        "min_data_in_leaf": "100", "min_sum_hessian_in_leaf": "10.0",
        "learning_rate": "0.1", "grow_policy": "depthwise",
        "hist_dtype": args.hist_dtype,
        "num_iterations": str(args.iters),
    }, require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))
    booster.train_chunk(args.iters)
    booster.flush_pipeline()
    T = len(booster.models)

    shards = max(int(args.serve_shards), 0)
    linger_us = max(int(args.predict_linger_us), 0)
    # a DENSER ladder than the offline default: coalesced batches land
    # between 1k and 64k under open-loop load, and the default ladder's
    # sparse top would pad every ~2k-row batch to 65536 (40x wasted
    # walk).  Still a closed compiled set — this is exactly the
    # predict_buckets knob doing its job for the online profile.
    buckets = (1, 32, 256, 2048, 16384, 65536)
    # the swap pair: engine A serves a PREFIX of the model, engine B the
    # full model — the realistic continued-training hot swap, and their
    # scores differ so a torn request cannot hide
    ta = max(T - 2, 1)
    eng_a = ServingEngine(booster.export_flat(ta), buckets=buckets,
                          shards=shards, linger_us=linger_us)
    eng_b = ServingEngine(booster.export_flat(), buckets=buckets,
                          shards=shards, linger_us=linger_us)

    pool_rows = 65536
    pool, _ = make_data(pool_rows, args.features, seed=7,
                        narrow_features=narrow)
    # per-request references for the misscore check: every request is a
    # contiguous pool slice, so its exact expected scores are a column
    # slice of one of these
    ref_a = eng_a.scores(pool)
    ref_b = eng_b.scores(pool)
    eng_a.warmup()
    eng_b.warmup()             # double-buffer: compiled BEFORE the load
    progs0 = len(costmodel.phase_program_records("predict"))

    # closed-loop capacity estimate prices the offered open-loop rate
    req_rows = 64
    t0 = time.perf_counter()
    calls = 0
    while time.perf_counter() - t0 < 0.5 or calls < 3:
        eng_a.scores(pool[:1024])
        calls += 1
    cap = 1024 * calls / (time.perf_counter() - t0)
    # offer well below the closed-loop estimate: at ~capacity the
    # bounded queue saturates and p99 measures backpressure, not the
    # serving path.  0.3x keeps the generator truly open-loop.
    offered = max(cap * 0.3, req_rows * 10.0)
    interval = req_rows / offered

    def open_loop(front, duration_s, swap_after_s=None, swap_to=None):
        """Submit pool slices on the open-loop schedule; returns
        (records, swap_drain_s).  Arrivals follow the wall clock — a
        slow completion never delays the next submit."""
        import threading
        records = []
        start = time.perf_counter()
        next_t = start
        i = 0
        drain_box = {}
        swap_thread = None
        swapped = swap_after_s is None
        while time.perf_counter() - start < duration_s:
            if not swapped and time.perf_counter() - start >= swap_after_s:
                # the swap blocks until the drain-and-flip completes, so
                # it runs on its OWN thread: the open-loop schedule keeps
                # submitting INTO the drain window — that concurrency is
                # exactly what the zero-drop contract is about
                swap_thread = threading.Thread(
                    target=lambda: drain_box.__setitem__(
                        "drain", front.swap_engine(swap_to, warmup=False)))
                swap_thread.start()
                swapped = True
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            s = (i * req_rows) % (pool_rows - req_rows)
            rec = {"s": s, "n": req_rows, "t_sub": time.perf_counter()}
            fut = front.submit(pool[s:s + req_rows])
            fut.add_done_callback(
                lambda f, rec=rec: rec.__setitem__(
                    "t_done", time.perf_counter()))
            rec["fut"] = fut
            records.append(rec)
            next_t += interval
            i += 1
        if swap_thread is not None:
            swap_thread.join(60.0)
        return records, drain_box.get("drain")

    # ---- phase 1: steady open-loop load on engine A, interleaved
    # recorder-ON / recorder-OFF segments (ISSUE 16).  ON segments are
    # the shipped default-on state and provide the serve metrics; OFF
    # segments are the control that prices the recorder.  Every ON
    # segment arms a FRESH ring at the default size — a nonzero
    # trace_dropped_at_default therefore means a single ~2 s window
    # overflowed trace_ring_events, never an artifact of accumulation.
    lats, samples, requests = [], [], 0
    off_samples = []
    bench_sk = tracing.LatencySketch()  # bench's own submit→done lats
    wall_sk = None                      # recorder-side serve_wall_us
    dropped_at_default = 0
    for rep in range(2 * max(1, args.repeats)):
        on = rep % 2 == 0
        if on:
            tracing.arm()               # fresh DEFAULT-size ring
        front = ServingFront(eng_a, linger_us=linger_us)
        t0 = time.perf_counter()
        records, _ = open_loop(front, duration_s=2.0)
        front.close()
        wall = time.perf_counter() - t0
        done_rows = sum(r["n"] for r in records if "t_done" in r)
        if not on:
            off_samples.append(done_rows / wall)
            continue
        samples.append(done_rows / wall)
        seg_sk = tracing.LatencySketch()
        for r in records:
            if "t_done" in r:
                lat = r["t_done"] - r["t_sub"]
                lats.append(lat)
                seg_sk.record(1e6 * lat)
        # the cross-segment fold IS the sketch merge operator (the same
        # count addition that folds across threads/hosts)
        bench_sk.merge(seg_sk)
        requests += len(records)
        dropped_at_default += tracing.dropped()
        sk = tracing.sketch("serve_wall_us")
        if sk is not None:
            wall_sk = sk if wall_sk is None else wall_sk.merge(sk)
        tracing.disarm()

    # ---- phase 2: the mid-load hot swap (drain-and-flip, zero drops),
    # recorder armed so the swap/drain events land on the request
    # timeline; --trace-dump flushes this window's ring on disarm for
    # scripts/trace_report.py
    if args.trace_dump:
        os.makedirs(args.trace_dump, exist_ok=True)
    tracing.arm(dump_dir=args.trace_dump)
    front = ServingFront(eng_a, linger_us=linger_us)
    records, drain = open_loop(front, duration_s=2.0, swap_after_s=1.0,
                               swap_to=eng_b)
    front.close()
    dropped_at_default += tracing.dropped()
    sk = tracing.sketch("serve_wall_us")
    if sk is not None:
        wall_sk = sk if wall_sk is None else wall_sk.merge(sk)
    trace_dump_path = tracing.disarm()
    dropped = 0
    misscored = 0
    for r in records:
        fut = r["fut"]
        if not fut.done() or fut.exception() is not None:
            dropped += 1
            continue
        got = np.asarray(fut.result())
        s, n = r["s"], r["n"]
        if not (np.array_equal(got, ref_a[:, s:s + n])
                or np.array_equal(got, ref_b[:, s:s + n])):
            misscored += 1

    # ---- phase 3: live-monitor cost (ISSUE 20), interleaved monitor-ON
    # / monitor-OFF segments with the recorder armed in BOTH (the
    # shipped default) — the delta prices ONLY the monitor: the
    # per-batch score feed, the emitter's windowed differencing and the
    # JSONL append.  The ON segments also run a generous SLO (20x the
    # measured healthy p99) so a breach on a no-fault bench round is an
    # absolute perf_gate finding, and the last segment's A/A PSI rides
    # out as drift_aa_psi — the measured false-positive floor.
    from lightgbm_tpu import monitor
    mon_samples, mon_off_samples = [], []
    mon_breaches = 0
    mon_aa_psi = None
    mon_slo_us = 20.0 * bench_sk.quantile(0.99)
    with tempfile.TemporaryDirectory() as mon_td:
        for rep in range(2 * max(1, args.repeats)):
            on = rep % 2 == 0
            tracing.arm()               # recorder on in BOTH segments
            if on:
                monitor.arm(out_path=os.path.join(
                                mon_td, "monitor-%d.jsonl" % rep),
                            interval_s=0.5, slo_p99_us=mon_slo_us,
                            slo_window_s=6.0)
            front = ServingFront(eng_a, linger_us=linger_us)
            t0 = time.perf_counter()
            records, _ = open_loop(front, duration_s=2.0)
            front.close()
            wall = time.perf_counter() - t0
            done_rows = sum(r["n"] for r in records if "t_done" in r)
            if on:
                mon_samples.append(done_rows / wall)
                aa = monitor.aa_verdict(front._monitor_key)
                if aa["psi"] is not None:
                    mon_aa_psi = aa["psi"]
                mon_breaches += monitor.monitor_snapshot().get(
                    "breaches", 0)
                monitor.disarm()
            else:
                mon_off_samples.append(done_rows / wall)
            tracing.disarm()

    med = float(np.median(samples))
    off_med = float(np.median(off_samples)) if off_samples else med
    mon_med = float(np.median(mon_samples)) if mon_samples else med
    mon_off_med = (float(np.median(mon_off_samples))
                   if mon_off_samples else mon_med)
    # sketch percentiles, A/B-pinned against the sorted sample at the
    # same nearest-rank convention: agreement within the sketch's bucket
    # resolution (a factor sqrt(growth)) is a mathematical guarantee —
    # any violation is a sketch bug and aborts the bench
    lat_us = np.sort(np.asarray(lats)) * 1e6

    def _nearest_rank(q):
        r = min(len(lat_us) - 1, max(0, int(math.ceil(q * len(lat_us))) - 1))
        return float(lat_us[r])

    sk_p50, sk_p99 = bench_sk.quantile(0.50), bench_sk.quantile(0.99)
    tol = math.sqrt(bench_sk.growth) * (1.0 + 1e-9)
    for q, sk_v in ((0.50, sk_p50), (0.99, sk_p99)):
        exact = _nearest_rank(q)
        assert exact > 0 and 1.0 / tol <= sk_v / exact <= tol, (
            "latency sketch p%g %.1fus vs sorted %.1fus — outside bucket "
            "resolution (growth %g)"
            % (100 * q, sk_v, exact, bench_sk.growth))

    def _spread(vals, m):
        return (round((max(vals) - min(vals)) / m, 4)
                if vals and m > 0 else 0.0)

    out = {
        "metric": f"serve_rows_per_sec_higgs{train_rows // 1000}k_"
                  f"trees{T}_leaves{args.leaves}",
        "unit": "rows/sec",
        "host": costmodel.host_fingerprint(),
        "trees": T,
        "value": round(med, 2),
        "samples": [round(s, 2) for s in samples],
        "spread": round((max(samples) - min(samples)) / med, 4)
                  if med > 0 else 0.0,
        "serve_rows_per_sec": round(med, 2),
        "serve_spread": _spread(samples, med),
        "serve_p50_us": round(sk_p50, 1),
        "serve_p99_us": round(sk_p99, 1),
        "serve_p99_sketch_vs_sorted": round(sk_p99 / _nearest_rank(0.99),
                                            4),
        "serve_offered_rows_per_sec": round(offered, 2),
        "serve_requests": requests,
        "serve_linger_us": linger_us,
        "serve_recompiles": len(costmodel.phase_program_records("predict"))
                            - progs0,
        "serve_dropped": dropped,
        "serve_misscored": misscored,
        "serve_swap_drain_ms": round(1e3 * drain, 3)
                               if drain is not None else None,
        "serve_coalesced_batches": telemetry.counters().get(
            "serve/coalesced_batches", 0),
        "serve_mean_batch_rows": round(
            telemetry.counters().get("serve/coalesced_rows", 0)
            / max(telemetry.counters().get("serve/coalesced_batches", 1),
                  1), 1),
        "serve_shards_used": eng_a.shards,
        # recorder cost: throughput lost with the recorder armed, from
        # the interleaved ON/OFF medians (negative = noise; the gate's
        # must-not-grow band absorbs it)
        "trace_overhead_pct": round(100.0 * (off_med - med) / off_med, 2)
                              if off_med > 0 else 0.0,
        "trace_spread": max(_spread(samples, med),
                            _spread(off_samples, off_med)),
        "trace_dropped_at_default": int(dropped_at_default),
        # live-monitor cost (ISSUE 20): throughput lost with the monitor
        # armed on top of the recorder, from the phase-3 interleave —
        # must-not-grow in perf_gate with monitor_spread as its band
        "monitor_overhead_pct": round(
            100.0 * (mon_off_med - mon_med) / mon_off_med, 2)
            if mon_off_med > 0 else 0.0,
        "monitor_spread": max(_spread(mon_samples, mon_med),
                              _spread(mon_off_samples, mon_off_med)),
        # A/A PSI on the last monitored segment's own scores: the
        # measured drift false-positive floor (absolute perf_gate
        # finding above monitor.AA_PSI_BOUND)
        "drift_aa_psi": round(mon_aa_psi, 5)
                        if mon_aa_psi is not None else None,
        # breaches fired under a 20x-generous SLO on healthy load: any
        # nonzero on a round not declaring an induced fault is an
        # absolute perf_gate finding
        "monitor_slo_breaches": int(mon_breaches),
        "monitor_induced_fault": False,
    }
    if wall_sk is not None:
        # recorder-side enqueue→complete wall percentiles (the traced
        # identity's wall, vs the bench's submit→callback lats above)
        out["trace_wall_p99_us"] = round(wall_sk.quantile(0.99), 1)
    if trace_dump_path:
        out["trace_dump"] = trace_dump_path
    snap = telemetry.snapshot()
    if "roofline" in snap:
        out["roofline"] = snap["roofline"]
    if "compile" in snap:
        out["compile"] = snap["compile"]
    print(json.dumps(out))
    return 0


# keys the headline bench copies out of the --bench-ingest subprocess
# (perf_gate gates ingest_rows_per_sec; the A/B, H2D rate and RSS
# assertion ride along ungated)
INGEST_COPY_KEYS = (
    "ingest_rows_per_sec", "ingest_spread",
    "ingest_sync_rows_per_sec", "ingest_overlap_speedup",
    "ingest_h2d_gbps", "ingest_peak_rss_bytes",
    "ingest_rss_bound_bytes", "ingest_rss_ok", "ingest_trained_iters",
    # phase attribution (ISSUE 17): the recorded rounds EXPLAIN an
    # ingest_rows_per_sec move instead of just re-measuring it
    "ingest_parse_pct", "ingest_bin_pct", "ingest_h2d_pct",
    # parallel-parse lane (ISSUE 18): perf_gate turns
    # ingest_rows_per_sec into a must-GROW lane on rounds recording
    # ingest_workers > 1 and flags a silent resolve-to-serial
    "ingest_workers", "ingest_workers_effective",
    "ingest_serial_rows_per_sec", "ingest_serial_parse_pct",
)


def bench_wire(args) -> int:
    """Hybrid/voting wire-bytes lane (ISSUE 9): train the bench schema
    (--features, --max-bin) under ``tree_learner=data`` (pure-DP psum),
    ``hybrid`` and ``voting`` on a simulated (2, 2) mesh and print one
    JSON line with the telemetry interconnect block's LOGICAL
    ``wire_bytes_per_iter`` per learner plus the per-site est-bytes.

    Not a timing lane: the numbers are deterministic (traced shapes x
    loop estimates).  The GATED copy of this series rides the MULTICHIP
    trajectory (__graft_entry__._wire_smoke prints the MULTICHIP_WIRE
    line perf_gate.py checks); this lane reads the same numbers at
    arbitrary schemas, next to the comm-cost model in PROFILE.md
    (F·B·4B DP vs F·B/fs hybrid vs 2k·B voting per split).

    Histograms are pinned to float32 regardless of --hist-dtype: under
    int8 the int accumulators deliberately ride the FULL data-axis psum
    (voting_seams — local caches would break the int-domain bit-identity
    chain), so the voting wire saving the lane prices exists on the
    float paths only."""
    import sys as _sys

    import __graft_entry__ as graft
    device_type = graft._provision_devices(4)

    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.utils import log

    log.set_stream(_sys.stderr)
    log.set_level(log.WARNING)

    rows = min(args.rows, 65536)     # logical bytes don't scale with rows
    x, y = make_data(rows, args.features)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)
    out = graft.measure_wire_bytes(
        ds, device_type,
        {"objective": "binary", "num_leaves": str(args.leaves),
         "min_data_in_leaf": "4", "min_sum_hessian_in_leaf": "0.1",
         "learning_rate": "0.1", "grow_policy": args.grow_policy,
         "hist_dtype": "float32"},
        (("data", {}),
         ("hybrid", {"feature_shards": "2"}),
         # 4k < F/fs — the leaf-wise voting-beats-hybrid regime (the
         # depthwise schedules have no subtraction trick to amortize, so
         # there 2k < F/fs suffices)
         ("voting", {"feature_shards": "2", "top_k": "2"})))
    out.update({"metric": "wire_2x2"})
    out["schema"].update({"rows": rows, "leaves": args.leaves,
                          "hist_dtype": "float32"})
    w = out["wire_bytes_per_iter"]
    out["ok"] = bool(0 < w.get("hybrid", 0) < w.get("data", 0)
                     and 0 < w.get("voting", 0) < w.get("hybrid", 0))
    print(json.dumps(out))
    return 0 if out["ok"] else 1


def bench_ingest(args) -> int:
    """Streaming-ingestion lane (ISSUE 8, io/streaming.py): rows/sec for
    the full chunked parse→bin→HBM pipeline, the double-buffer on/off
    A/B (``LGBM_TPU_INGEST_SYNC=1``), effective H2D GB/s, and the
    peak-host-RSS assertion — a streamed load of a dataset larger than
    one chunk must never approach the resident loader's full [N, F]
    float64 materialization (``ingest_rss_ok``; reported null when the
    scale is too small to discriminate against the interpreter's own
    baseline RSS).  The CSV source is written in bounded row blocks for
    the same reason: the lane prices the LOADER's memory profile, not
    the generator's."""
    import os
    import resource
    import tempfile

    import jax  # noqa: F401  (device init before timing)
    from lightgbm_tpu import costmodel, telemetry
    from lightgbm_tpu.config import IOConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.utils import log

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)
    telemetry.enable()
    telemetry.reset()

    rows = args.rows
    narrow = (args.narrow_features if args.narrow_features >= 0
              else (args.features * 6) // 7)
    tmpdir = tempfile.mkdtemp(prefix="bench_ingest_")
    path = os.path.join(tmpdir, "ingest.csv")
    block = 200_000
    with open(path, "w") as f:
        for s in range(0, rows, block):
            n = min(block, rows - s)
            x, y = make_data(n, args.features, seed=1000 + s // block,
                             narrow_features=narrow)
            f.write("\n".join(
                "%d," % y[i] + ",".join("%.6g" % v for v in x[i])
                for i in range(n)) + "\n")
            del x, y
    csv_bytes = os.path.getsize(path)

    def _rss_bytes() -> int:
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    rss_after_write = _rss_bytes()

    workers = max(int(getattr(args, "ingest_workers", 0)), 0)

    def load_once(sync: bool, n_workers: int = 0):
        if sync:
            os.environ["LGBM_TPU_INGEST_SYNC"] = "1"
        else:
            os.environ.pop("LGBM_TPU_INGEST_SYNC", None)
        kw = {"ingest_workers": n_workers} if n_workers > 1 else {}
        t0 = time.perf_counter()
        ds = Dataset.load_train(IOConfig(
            data_filename=path, streaming="true",
            ingest_chunk_rows=args.ingest_chunk_rows, **kw))
        return ds, rows / (time.perf_counter() - t0)

    # one warm load compiles the update programs; then timed repeats
    ds, _ = load_once(sync=False, n_workers=workers)
    samples = []
    serial_med = serial_parse_pct = None
    if workers > 1:
        # serial reference lane (ISSUE 18): when the timed lane runs
        # the byte-range worker pool, price the serial loader on the
        # SAME file in the same process — and INTERLEAVE the two lanes'
        # repeats, so minute-scale host drift hits both lanes equally
        # and the within-record speedup ratio (perf_gate's must-GROW
        # baseline) stays honest.  The serial loads never rebind ``ds``:
        # the workers-lane dataset is the one proved below by training.
        phase_us = {k: 0 for k in ("parse", "bin", "h2d")}
        sp = {k: 0 for k in ("parse", "bin", "h2d")}
        h2d = 0
        serial_samples = []
        for _ in range(max(1, args.repeats)):
            c0 = dict(telemetry.counters())
            ds, rps = load_once(sync=False, n_workers=workers)
            c1 = dict(telemetry.counters())
            samples.append(rps)
            h2d += (c1.get("ingest/h2d_bytes", 0)
                    - c0.get("ingest/h2d_bytes", 0))
            for k in phase_us:
                phase_us[k] += (c1.get("ingest/%s_us" % k, 0)
                                - c0.get("ingest/%s_us" % k, 0))
            _, srps = load_once(sync=False)
            s1 = dict(telemetry.counters())
            serial_samples.append(srps)
            for k in sp:
                sp[k] += (s1.get("ingest/%s_us" % k, 0)
                          - c1.get("ingest/%s_us" % k, 0))
        serial_med = float(np.median(serial_samples))
        sp_total = sum(sp.values())
        serial_parse_pct = (round(100.0 * sp["parse"] / sp_total, 2)
                            if sp_total > 0 else None)
    else:
        c0 = dict(telemetry.counters())
        for _ in range(max(1, args.repeats)):
            ds, rps = load_once(sync=False, n_workers=workers)
            samples.append(rps)
        c1 = dict(telemetry.counters())
        h2d = (c1.get("ingest/h2d_bytes", 0)
               - c0.get("ingest/h2d_bytes", 0))
        # tokenizer/bin/H2D attribution over the timed (async) repeats —
        # percentages of the accounted pass-2 time, so the three keys
        # sum to ~100 and a regression names its phase
        phase_us = {k: c1.get("ingest/%s_us" % k, 0)
                    - c0.get("ingest/%s_us" % k, 0)
                    for k in ("parse", "bin", "h2d")}
    phase_total = sum(phase_us.values())
    timed_s = sum(rows / s for s in samples)
    sync_samples = [load_once(sync=True, n_workers=workers)[1]
                    for _ in range(max(1, args.repeats))]
    os.environ.pop("LGBM_TPU_INGEST_SYNC", None)

    # RSS snapshot HERE, before the end-to-end train below: the
    # assertion prices the LOADER's memory profile — trainer
    # allocations (scores, histograms, XLA compile arenas) must not be
    # able to tip ingest_rss_ok over the threshold
    peak_rss = _rss_bytes()

    # end-to-end proof: the streamed (device-resident) dataset trains
    trained = 0
    if args.iters > 0:
        from lightgbm_tpu.config import OverallConfig
        from lightgbm_tpu.models.gbdt import GBDT
        from lightgbm_tpu.objectives import create_objective
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": str(args.leaves),
                 "min_data_in_leaf": "100", "learning_rate": "0.1",
                 "hist_dtype": args.hist_dtype,
                 "grow_policy": args.grow_policy}, require_data=False)
        booster = GBDT()
        booster.init(cfg.boosting_config, ds,
                     create_objective(cfg.objective_type,
                                      cfg.objective_config))
        for _ in range(min(2, args.iters)):
            booster.train_one_iter(is_eval=False)
        trained = len(booster.models)

    rss_bound = rows * args.features * 8   # the resident [N, F] float64
    # the assertion only discriminates when the full matrix would
    # visibly exceed what the process already held (imports + CSV write
    # buffers); tiny lanes report null rather than a vacuous pass.  The
    # threshold is HALF the resident matrix: a regression that
    # re-materializes the full [N, F] float64 lands at about
    # rss_after_write + rss_bound, and allocator reuse of freed write
    # buffers can shave it just under a full-bound threshold — 0.5x
    # still passes every streamed load (one chunk ≪ half the matrix)
    # while failing the exact regression this guards against
    rss_ok = (bool(peak_rss < rss_after_write + 0.5 * rss_bound)
              if rss_bound > max(rss_after_write, 1) else None)

    med = float(np.median(samples))
    sync_med = float(np.median(sync_samples))
    out = {
        "metric": f"ingest_rows_per_sec_{rows // 1000}k_f{args.features}",
        "unit": "rows/sec",
        "host": costmodel.host_fingerprint(),
        "value": round(med, 2),
        "samples": [round(s, 2) for s in samples],
        "spread": round((max(samples) - min(samples)) / med, 4)
        if med > 0 else 0.0,
        "csv_bytes": csv_bytes,
        "ingest_chunk_rows": args.ingest_chunk_rows,
        "ingest_rows_per_sec": round(med, 2),
        "ingest_sync_rows_per_sec": round(sync_med, 2),
        "ingest_overlap_speedup": round(med / max(sync_med, 1e-9), 4),
        "ingest_h2d_gbps": round(h2d / max(timed_s, 1e-9) / 1e9, 4),
        "ingest_peak_rss_bytes": peak_rss,
        "ingest_rss_bound_bytes": rss_bound,
        "ingest_rss_ok": rss_ok,
        "ingest_trained_iters": trained,
        "ingest_parse_pct": (round(100.0 * phase_us["parse"]
                                   / phase_total, 2)
                             if phase_total > 0 else None),
        "ingest_bin_pct": (round(100.0 * phase_us["bin"] / phase_total, 2)
                           if phase_total > 0 else None),
        "ingest_h2d_pct": (round(100.0 * phase_us["h2d"] / phase_total, 2)
                           if phase_total > 0 else None),
    }
    if workers > 1:
        out["ingest_workers"] = workers
        out["ingest_workers_effective"] = int(
            getattr(ds, "ingest_workers_effective", 1))
        out["ingest_serial_rows_per_sec"] = round(serial_med, 2)
        out["ingest_serial_parse_pct"] = serial_parse_pct
    out["ingest_spread"] = out["spread"]
    print(json.dumps(out))
    try:
        os.unlink(path)
        os.rmdir(tmpdir)
    except OSError:
        pass
    return 0


# keys the headline bench copies out of the --bench-ckpt subprocess
# (scripts/perf_gate.py: ckpt_overhead_pct rides the must-not-grow
# latency lane; ckpt_restore_exact recorded False on ANY round is an
# ABSOLUTE finding — the bit-identical same-topology restore contract)
CKPT_COPY_KEYS = (
    "ckpt_overhead_pct", "ckpt_spread", "ckpt_restore_exact",
    "ckpt_writes", "ckpt_dropped", "ckpt_interval",
    "ckpt_off_iters_per_sec", "ckpt_on_iters_per_sec",
)


def bench_ckpt(args) -> int:
    """Checkpoint-cost lane (ISSUE 14): price asynchronous periodic
    checkpointing against the identical run with it off, and pin the
    restore contract.

    Two numbers: ``ckpt_overhead_pct`` — the median percent slowdown of
    ``run_training`` with ``checkpoint_interval=1`` (every iteration, the
    worst case; the async writer thread serializes + writes off the hot
    loop, so this prices exactly the snapshot cost the loop cannot hide)
    — and ``ckpt_restore_exact`` — True iff a kill-free
    train→checkpoint→fresh-booster-restore→finish run reproduces the
    uninterrupted run's model text AND scores bitwise on the same
    topology."""
    import os
    import tempfile

    import jax  # noqa: F401  (device init before timing)
    from lightgbm_tpu import costmodel, telemetry
    from lightgbm_tpu import checkpoint as ckpt_mod
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)
    telemetry.enable()
    telemetry.reset()

    train_rows = min(args.rows, 1_000_000)
    iters = min(args.iters, 64)
    narrow = (args.narrow_features if args.narrow_features >= 0
              else (args.features * 6) // 7)
    x, y = make_data(train_rows, args.features, narrow_features=narrow)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)

    base_params = {
        "objective": "binary",
        "num_leaves": str(args.leaves),
        "min_data_in_leaf": "100",
        "min_sum_hessian_in_leaf": "10.0",
        "learning_rate": "0.1",
        "grow_policy": args.grow_policy,
        "hist_dtype": args.hist_dtype,
    }

    def build(extra=None):
        params = dict(base_params)
        if extra:
            params.update(extra)
        cfg = OverallConfig()
        cfg.set(params, require_data=False)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config))
        return b

    def timed_run(extra=None):
        b = build(extra)
        t0 = time.perf_counter()
        b.run_training(iters, is_eval=False)
        import jax as _jax
        _jax.block_until_ready(b.score)
        return iters / (time.perf_counter() - t0), b

    # warmup compiles the shared chunk programs for both arms
    timed_run()
    off_samples, on_samples, overheads = [], [], []
    writes = 0
    with tempfile.TemporaryDirectory() as td:
        for r in range(max(1, args.repeats)):
            off, _ = timed_run()
            cdir = os.path.join(td, "r%d" % r)
            on, b_on = timed_run({"checkpoint_interval": "1",
                                  "checkpoint_dir": cdir,
                                  "checkpoint_keep": "2"})
            # checkpoints actually WRITTEN (not the post-prune retained
            # count): the booster records its writer's totals at close
            writes = max(writes,
                         (b_on._ckpt_stats or {}).get("written", 0))
            dropped = (b_on._ckpt_stats or {}).get("dropped", 0)
            off_samples.append(off)
            on_samples.append(on)
            overheads.append(100.0 * (off - on) / on)
        # restore contract: uninterrupted vs checkpoint-resumed, bitwise
        ref, b_ref = timed_run()
        ref_trees = [t.to_string() for t in b_ref.models]
        ref_score = np.asarray(b_ref.score)
        cdir = os.path.join(td, "restore")
        half = max(iters // 2, 1)
        b_half = build({"checkpoint_interval": "1",
                        "checkpoint_dir": cdir})
        b_half.run_training(half, is_eval=False)
        latest = ckpt_mod.latest_checkpoint(cdir)
        b_res = build()
        b_res.restore_checkpoint(ckpt_mod.load_checkpoint(latest))
        b_res.run_training(iters - b_res.iter, is_eval=False)
        exact = (ref_trees == [t.to_string() for t in b_res.models]
                 and np.array_equal(ref_score, np.asarray(b_res.score)))

    med_over = float(np.median(overheads))
    out = {
        "metric": f"ckpt_overhead_higgs{train_rows // 1000}k_"
                  f"leaves{args.leaves}",
        "unit": "pct",
        "host": costmodel.host_fingerprint(),
        "ckpt_interval": 1,
        # clamp at 0: a negative sample is timing noise, and the gated
        # must-not-grow lane wants the cost, not the noise sign
        "ckpt_overhead_pct": round(max(med_over, 0.0), 4),
        # spread in percentage POINTS (the lane's own noise band)
        "ckpt_spread": round(max(overheads) - min(overheads), 4),
        "ckpt_overhead_samples": [round(o, 4) for o in overheads],
        "ckpt_off_iters_per_sec": round(float(np.median(off_samples)), 4),
        "ckpt_on_iters_per_sec": round(float(np.median(on_samples)), 4),
        "ckpt_writes": int(writes),
        "ckpt_dropped": int(dropped),
        "ckpt_restore_exact": bool(exact),
    }
    telemetry.disable()
    print(json.dumps(out))
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    # 11M rows is the headline scale (BASELINE.md north star: Higgs-11M,
    # num_leaves=255); pass --rows 1000000 for the quick tuning scale
    parser.add_argument("--rows", type=int, default=11_000_000)
    parser.add_argument("--features", type=int, default=28)
    parser.add_argument("--narrow-features", type=int, default=-1,
                        help="low-cardinality (<=64 distinct) columns in "
                             "the generated table; -1 = 6/7 of the "
                             "features (the r06 mixed-cardinality "
                             "headline schema, see make_data), 0 = the "
                             "historical all-continuous table")
    parser.add_argument("--leaves", type=int, default=255)
    parser.add_argument("--max-bin", type=int, default=255)
    parser.add_argument("--iters", type=int, default=64,
                        help="iterations per chunk; one chunk warms up "
                             "(compiles) and one chunk is timed.  Bigger "
                             "chunks amortize the per-dispatch host "
                             "round-trip (16: 7.2, 32: 7.7, 64: 7.9 "
                             "iters/sec at the 1M default)")
    parser.add_argument("--grow-policy", default="depthwise",
                        choices=["depthwise", "leafwise"],
                        help="depthwise = TPU level-batched histograms "
                             "(headline); leafwise = reference-parity order")
    parser.add_argument("--hist-chunk", type=int, default=0,
                        help="histogram scan row-chunk (0 = policy default)")
    parser.add_argument("--hist-dtype", default="int8",
                        choices=["float32", "bfloat16", "int8"],
                        help="int8 = quantized-gradient Pallas kernel, the "
                             "tuned TPU configuration (held-out AUC within "
                             "0.005 of the reference binary — gated by "
                             "tests/test_auc_parity.py); float32 is the "
                             "reference-exact mode")
    parser.add_argument("--skip-parity", action="store_true",
                        help="skip the additional reference-parity "
                             "(leafwise f32) timing pass")
    parser.add_argument("--repeats", type=int, default=3,
                        help="timed measurement rounds (one dataset build "
                             "+ compile, N timing rounds; applies to both "
                             "grow policies).  The JSON value is the "
                             "median; all samples are reported so drift "
                             "in the tunneled runtime's dispatch overhead "
                             "is visible (VERDICT r4 weak #5).  Default 3 "
                             "(r06): the HEADLINE now carries measured "
                             "samples/spread like the satellite lanes, so "
                             "perf_gate's noise band on it is measured "
                             "rather than defaulted")
    parser.add_argument("--mixed-bin", default="auto",
                        choices=["auto", "true", "false"],
                        help="mixed-bin feature packing (per-bin-width-"
                             "class histogram passes); auto = on whenever "
                             "the table mixes narrow and wide features")
    parser.add_argument("--tree-learner", default="serial",
                        choices=["serial", "data", "hybrid", "voting"],
                        help="train the headline on a parallel learner "
                             "over a simulated 4-device CPU mesh "
                             "(hybrid/voting: (2,2) with "
                             "feature_shards=2) — the "
                             "mixedbin_hybrid_iters_per_sec lane runs "
                             "hybrid with mixed_bin=true so the gated "
                             "series carries the composed "
                             "packing-on-the-2-D-mesh configuration")
    parser.add_argument("--pipeline", default="readback",
                        choices=["readback", "off"],
                        help="pipelined boosting: double-buffer the next "
                             "chunk/iteration dispatch against the "
                             "current model readback (bit-identical "
                             "results; 'off' = synchronous A/B)")
    parser.add_argument("--bench-ingest", action="store_true",
                        help="streaming-ingestion benchmark (ISSUE 8): "
                             "write a --rows CSV in bounded blocks, then "
                             "measure the chunked parse->bin->HBM "
                             "pipeline's rows/sec (double-buffer on/off "
                             "A/B, effective H2D GB/s, peak-host-RSS "
                             "assertion, 2-iteration end-to-end train)")
    parser.add_argument("--ingest-chunk-rows", type=int, default=200_000,
                        help="streaming loader chunk length for "
                             "--bench-ingest (the ingest_chunk_rows= "
                             "knob)")
    parser.add_argument("--ingest-workers", type=int, default=0,
                        help="byte-range parse worker processes for "
                             "--bench-ingest (the ingest_workers= knob; "
                             "0/1 = serial loader; >1 additionally "
                             "records the serial reference lane)")
    parser.add_argument("--bench-wire", action="store_true",
                        help="wire-bytes lane (ISSUE 9): tree_learner="
                             "data vs hybrid vs voting on a simulated "
                             "(2,2) mesh at the bench schema; prints the "
                             "per-learner logical wire_bytes_per_iter "
                             "and per-site interconnect est-bytes (the "
                             "gated copy rides the MULTICHIP "
                             "trajectory)")
    parser.add_argument("--bench-predict", action="store_true",
                        help="serving benchmark (ISSUE 7): train a model "
                             "(rows clamped to 1M, --iters trees), then "
                             "measure the compiled serving engine's "
                             "predictions/sec and p50/p99 latency per "
                             "batch bucket (1/32/1k/64k), f32 and int8, "
                             "plus the legacy per-tree-scan A/B at 64k")
    parser.add_argument("--bench-serve", action="store_true",
                        help="elastic-serving benchmark (ISSUE 13): p99 "
                             "latency + rows/sec under a concurrent "
                             "open-loop load generator through the "
                             "coalescing ServingFront, plus a mid-load "
                             "drain-and-flip hot swap with dropped/"
                             "misscored counts (both must be 0)")
    parser.add_argument("--bench-ckpt", action="store_true",
                        help="checkpoint-cost benchmark (ISSUE 14): "
                             "run_training with checkpoint_interval=1 vs "
                             "off (the ckpt_overhead_pct must-not-grow "
                             "lane) plus the bit-identical restore "
                             "contract (ckpt_restore_exact; False fails "
                             "the perf gate absolutely)")
    parser.add_argument("--serve-shards", type=int, default=0,
                        help="tree-shard the --bench-serve engines over "
                             "this many devices (0 = single-device; "
                             "sharded scores are bit-equal by contract)")
    parser.add_argument("--predict-linger-us", type=int, default=500,
                        help="ServingFront max coalescing linger for "
                             "--bench-serve (the predict_linger_us knob)")
    parser.add_argument("--trace-dump", default="",
                        help="flight-recorder dump dir for --bench-serve "
                             "(the swap-phase ring flushes there as JSONL "
                             "on close; render/validate with "
                             "scripts/trace_report.py)")
    args = parser.parse_args()
    if args.bench_ingest:
        return bench_ingest(args)
    if args.bench_predict:
        return bench_predict(args)
    if args.bench_serve:
        if args.serve_shards > 1:
            import __graft_entry__ as graft
            graft._provision_devices(max(args.serve_shards, 4))
        return bench_serve(args)
    if args.bench_wire:
        return bench_wire(args)
    if args.bench_ckpt:
        return bench_ckpt(args)
    if (args.hist_dtype != "int8" and args.rows > 4_000_000
            and args.grow_policy == "depthwise"):
        # one fused dispatch of --iters f32 iterations at this scale would
        # cross the environment's ~60 s per-dispatch execution watchdog
        # (BASELINE.md); clamp to a safe chunk length (coefficient = the
        # measured f32x2 Pallas per-row-per-iteration cost)
        safe = max(1, int(40.0 / (args.rows * 1.8e-7)))
        if args.iters > safe:
            print(f"clamping --iters {args.iters} -> {safe} "
                  f"(f32 dispatch watchdog, see BASELINE.md)",
                  file=sys.stderr)
            args.iters = safe

    device_type = ""
    if args.tree_learner != "serial":
        import __graft_entry__ as graft
        device_type = graft._provision_devices(4)

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log

    # stdout carries exactly ONE JSON line; all library logs go to stderr
    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)

    # telemetry WITHOUT a sink: kernel-route counters and trace/compile
    # spans are recorded (route decisions fire during the warmup compile),
    # and the only cost inside the timed region is one host perf_counter
    # span per chunk — the JSON gains a phase-breakdown block for free.
    # memory=True adds the span-boundary HBM gauges (a host-side stats
    # read per chunk) so BENCH_*.json rounds carry the memory trajectory;
    # the armed telemetry also resolves health="auto" ON, so the chunk
    # programs accumulate the in-program health vector (a handful of [C,N]
    # reductions per iteration — noise next to the histogram passes).
    # DEPTHWISE runs fence the spans (ISSUE 4): unfenced spans on the
    # async TPU time the chunk DISPATCH, not its execution, and the
    # roofline attained rates would be meaningless.  Total timed wall is
    # unchanged — run_chunks block_until_ready's right after the span
    # either way, the wait just attributes to train_chunk instead of the
    # gap.  Leaf-wise stays unfenced: its per-iteration path overlaps
    # gradient/grow/readback dispatches by design, and fencing would
    # serialize exactly the overlap prior BENCH rounds measured.
    telemetry.enable(memory=True,
                     fence=(args.grow_policy == "depthwise"))

    narrow = (args.narrow_features if args.narrow_features >= 0
              else (args.features * 6) // 7)
    x, y = make_data(args.rows, args.features, narrow_features=narrow)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)

    def run_config(grow_policy: str, hist_dtype: str, iters: int):
        """Train one configuration (fresh booster, shared dataset) and
        return ``(samples, health_summary)``: per-round timed iters/sec
        samples — one warmup round compiles + caches the programs, then
        ``--repeats`` identical rounds are timed (median/spread computed
        by the caller) — plus the booster's cumulative health totals
        (None when the monitor was off, e.g. the leaf-wise path)."""
        params = {
            "objective": "binary",
            "num_leaves": str(args.leaves),
            "min_data_in_leaf": "100",
            "min_sum_hessian_in_leaf": "10.0",
            "learning_rate": "0.1",
            "grow_policy": grow_policy,
            "hist_chunk": str(args.hist_chunk),
            "hist_dtype": hist_dtype,
            "num_iterations": str(2 * iters),
            "mixed_bin": args.mixed_bin,
            "pipeline": args.pipeline,
        }
        if grow_policy == "leafwise":
            # leaf-wise times train_one_iter per iteration: the health
            # monitor's separate dispatch + host fetch per iteration is
            # exactly the tunneled-TPU round-trip cost this path is
            # dominated by, so it would skew the headline vs prior BENCH
            # rounds — health off here (the chunked path keeps it: its
            # vector rides IN the fused program and the readback)
            params["health"] = "false"
            # keep every leaf-wise dispatch under the environment's ~60 s
            # execution watchdog: segment the per-tree split loop so each
            # dispatch stays ~30 s (bit-identical trees,
            # models/grower.grow_tree_segmented).  Coefficients = measured
            # per-row-per-split pass cost on v5e per kernel (leaf-wise
            # passes are single-column, so f32's 5-stat single pass costs
            # ~one bf16 pass; int8 runs at 2x the bf16 rate).
            per_row = {"float32": 1.6e-8, "bfloat16": 1.5e-8,
                       "int8": 9e-9}[hist_dtype]
            split_s = args.rows * per_row
            segs = max(1, math.ceil((args.leaves - 1) * split_s / 30.0))
            params["leafwise_segments"] = str(segs)
        if args.tree_learner != "serial":
            params.update({"tree_learner": args.tree_learner,
                           "num_machines": "4",
                           "device_type": device_type})
            if args.tree_learner in ("hybrid", "voting"):
                params["feature_shards"] = "2"
        cfg = OverallConfig()
        cfg.set(params, require_data=False)

        booster = GBDT()
        objective = create_objective(cfg.objective_type,
                                     cfg.objective_config)
        learner = None
        if args.tree_learner != "serial":
            from lightgbm_tpu.parallel import create_parallel_learner
            learner = create_parallel_learner(cfg)
        booster.init(cfg.boosting_config, ds, objective, learner=learner)
        run_config.mixed_bin_on = booster._pack_spec is not None

        # leaf-wise runs per-iteration: a fused leaf-wise chunk is one
        # dispatch of k x 254 histogram passes, which is both slower than
        # per-iteration dispatch AND crosses the environment's ~60 s
        # per-dispatch execution watchdog at production shapes
        # (BASELINE.md)
        if grow_policy == "leafwise":
            # per-iteration dispatches: warm up (compile) with 2
            # iterations, then time iteration by iteration under a wall
            # budget — the tunneled-TPU environment's per-dispatch
            # execution watchdog (~60 s, BASELINE.md) and its variable
            # dispatch overhead make a fixed iteration count fragile
            for _ in range(2):
                if booster.train_one_iter(is_eval=False):
                    raise SystemExit("training stopped during warmup")
            jax.block_until_ready(booster.score)
            samples = []
            for rep in range(max(1, args.repeats)):
                done = 0
                stopped = False
                start = time.perf_counter()
                while done < iters and (done == 0
                                        or time.perf_counter() - start
                                        < 60.0):
                    if booster.train_one_iter(is_eval=False):
                        stopped = True
                        break
                    jax.block_until_ready(booster.score)
                    done += 1
                elapsed = time.perf_counter() - start
                if stopped:
                    # no splittable leaf.  First round: the rate would be
                    # meaningless (and the aborted attempt's wall time
                    # must not count).  Later rounds only ran because
                    # --repeats extended training past the point round 4
                    # benchmarked fine — report the full rounds we have
                    # rather than aborting the whole parity pass.
                    if samples:
                        break
                    raise SystemExit(
                        "training stopped (no splittable leaf) — bench "
                        "numbers would be meaningless; use more rows or "
                        "fewer constraints")
                if done == 0:
                    raise RuntimeError("no leafwise iteration completed")
                samples.append(done / elapsed)
            booster.flush_pipeline()
            return samples, booster.health_summary()

        def run_chunks():
            booster.train_chunk(iters)
            jax.block_until_ready(booster.score)

        run_chunks()
        samples = []
        for _ in range(max(1, args.repeats)):
            start = time.perf_counter()
            run_chunks()
            samples.append(iters / (time.perf_counter() - start))
        # drain the deferred chunk readback (pipeline=readback) so the
        # health/model state below is complete
        booster.flush_pipeline()
        return samples, booster.health_summary()

    run_config.mixed_bin_on = False
    samples, health_summary = run_config(args.grow_policy, args.hist_dtype,
                                         args.iters)
    iters_per_sec = float(np.median(samples))
    snap = telemetry.snapshot()
    from lightgbm_tpu import costmodel
    out = {
        "metric": f"boosting_iters_per_sec_higgs{args.rows // 1000}k_"
                  f"leaves{args.leaves}",
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec",
        # self-describing host metadata (ISSUE 4): BENCH_r*.json trajectory
        # entries carry the hardware/runtime they were measured on, so
        # scripts/perf_gate.py can refuse cross-hardware comparisons
        "host": costmodel.host_fingerprint(),
        "vs_baseline": round(
            iters_per_sec / reference_iters_per_sec(args.rows), 4),
        "vs_cuda": round(iters_per_sec / cuda_iters_per_sec(args.rows), 4),
        "cuda_anchor_iters_per_sec": cuda_iters_per_sec(args.rows),
        # mixed-bin resolution record (ISSUE 12): scripts/perf_gate.py
        # flags a hybrid/voting round whose config requested auto/true
        # but whose booster silently resolved the uniform layout
        "tree_learner": args.tree_learner,
        "mixed_bin_requested": args.mixed_bin,
        "mixedbin_expected": narrow > 0,
        "mixed_bin_on": bool(run_config.mixed_bin_on),
    }
    if len(samples) > 1 or max(1, args.repeats) > 1:
        # emit even when rounds were dropped (no-splittable-leaf early
        # stop): a single-sample result must be distinguishable from a
        # clean multi-round run or the drift record silently vanishes
        out["samples"] = [round(s, 4) for s in samples]
        out["spread"] = round((max(samples) - min(samples))
                              / iters_per_sec, 4)
        if len(samples) < args.repeats:
            out["repeats_dropped"] = args.repeats - len(samples)
    if args.rows < min(REFERENCE_CPU_ANCHORS):
        # sub-anchor scales extrapolate a cache-unfriendly per-row cost the
        # reference doesn't actually pay when the data fits in LLC
        out["vs_baseline_bound"] = "upper"

    # phase breakdown (telemetry): host phase wall times, trace/compile
    # attribution, and the kernel-route counters that record which
    # hist/partition kernels the compiled programs actually bake in —
    # the runtime answer to "did this run silently fall back to XLA?"
    out["phases"] = {
        "phase_times": {k: round(v, 4)
                        for k, v in sorted(snap["phase_times"].items())},
        "trace_times": {k: round(v, 4)
                        for k, v in sorted(snap["trace_times"].items())},
        "counters": dict(sorted(snap["counters"].items())),
    }

    # roofline + compile blocks (ISSUE 4): per-phase static program costs
    # (compiled.cost_analysis) joined to the measured spans — attained
    # FLOP/s, HBM GB/s, fraction-of-peak on TPU (peaks "unavailable"
    # elsewhere) — plus the compiled-program inventory (compile seconds,
    # persistent-cache hits, mid-run recompiles).  perf_gate tracks the
    # attained fractions across rounds next to the raw rates.
    if "roofline" in snap:
        out["roofline"] = snap["roofline"]
    if "compile" in snap:
        out["compile"] = snap["compile"]
    # interconnect block (ISSUE 5): per-collective-site logical bytes and
    # attained GB/s — present when a parallel learner's collective seams
    # were traced (multi-device runs); absent on serial runs
    if "interconnect" in snap:
        out["interconnect"] = snap["interconnect"]

    # memory trajectory (ISSUE 2): peak HBM watermark + dataset residency,
    # so BENCH_*.json rounds stop hand-measuring footprints (PROFILE.md)
    mem = snap.get("memory") or {}
    out["memory"] = {
        "peak_bytes_in_use": mem.get("peak_bytes_in_use", 0),
        "source": mem.get("source", "unavailable"),
        "residency": mem.get("residency", {}),
    }
    # health summary: anomaly count + NaN/saturation totals for the run
    # (health.HealthMonitor; nonzero anomalies invalidate a bench round)
    if health_summary is not None:
        out["health"] = {
            "anomalous_iterations": health_summary.get(
                "anomalous_iterations", 0),
            "grad_nan": health_summary.get("grad_nan", 0),
            "quant_sat": health_summary.get("quant_sat", 0),
            "score_max_abs": round(
                float(health_summary.get("score_max_abs", 0.0)), 4),
            "zero_gain_splits": health_summary.get("zero_gain_splits", 0),
        }

    # Additional configurations run as SUBPROCESSES: a leaf-wise 255-leaf
    # tree is ONE dispatch, and when the tunneled TPU's dispatch overhead
    # degrades (observed: ~3 s/iter one day, ~56 s/iter another on
    # identical code) a dispatch can cross the ~60 s execution watchdog
    # and kill the TPU worker — an add-on row must never take the
    # headline number down with it.
    def sub_bench(tag, extra_args, keys):
        import os
        import subprocess
        cmd = [sys.executable, os.path.abspath(__file__),
               "--rows", str(args.rows), "--features", str(args.features),
               "--narrow-features", str(narrow),
               "--leaves", str(args.leaves),
               "--hist-chunk", str(args.hist_chunk),
               "--skip-parity", "--repeats", "3"] + extra_args
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, check=True)
            sub = json.loads(res.stdout.strip().splitlines()[-1])
            for out_key, sub_key in keys:
                if sub_key in sub:
                    out[out_key] = sub[sub_key]
        except Exception as e:
            detail = f"{type(e).__name__}: {e}"
            stderr_tail = getattr(e, "stderr", None)
            if stderr_tail:
                detail += " | stderr: " + stderr_tail[-400:]
            out[f"{tag}_error"] = detail[:600]

    run_parity = (not args.skip_parity
                  and (args.grow_policy, args.hist_dtype) != ("leafwise",
                                                              "float32"))
    run_maxbin63 = not args.skip_parity and args.max_bin == 255
    # quantized leaf-wise parity mode: the compacted grower with int8
    # histograms — prices whether the per-pass quantize/pack overhead
    # (fixed cost per histogram pass) still binds now that leaf-wise
    # passes run over bucketed segments instead of full sweeps
    run_leafwise_int8 = (not args.skip_parity
                         and (args.grow_policy,
                              args.hist_dtype) != ("leafwise", "int8"))
    run_mixedbin = not args.skip_parity and narrow > 0
    if run_parity or run_maxbin63 or run_leafwise_int8 or run_mixedbin:
        # the parent's copies of the data are no longer needed; each child
        # rebuilds them, and holding both doubles peak host memory (~2.5 GB
        # of float64 features at the 11M default)
        del x, y, ds

    if run_parity:
        # the headline stacks two documented semantic departures from the
        # reference (depthwise level order + int8 quantized gradients,
        # both AUC-gated); price the reference-parity configuration
        # (leafwise, f32) in the same JSON (VERDICT r2 weak #2).
        # median-of-3 + spread: the runtime's dispatch overhead drifts
        # across days on identical code (VERDICT r4 weak #5)
        parity_iters = min(args.iters, 8 if args.rows > 4_000_000 else 16)
        sub_bench("parity",
                  ["--max-bin", str(args.max_bin),
                   "--iters", str(parity_iters),
                   "--grow-policy", "leafwise",
                   "--hist-dtype", "float32"],
                  [("parity_leafwise_f32_iters_per_sec", "value"),
                   ("parity_vs_baseline", "vs_baseline"),
                   ("parity_vs_cuda", "vs_cuda"),
                   ("parity_samples", "samples"),
                   ("parity_spread", "spread")])

    if run_leafwise_int8:
        lw8_iters = min(args.iters, 8 if args.rows > 4_000_000 else 16)
        sub_bench("leafwise_int8",
                  ["--max-bin", str(args.max_bin),
                   "--iters", str(lw8_iters),
                   "--grow-policy", "leafwise",
                   "--hist-dtype", "int8"],
                  [("leafwise_int8_iters_per_sec", "value"),
                   ("leafwise_int8_vs_baseline", "vs_baseline"),
                   ("leafwise_int8_samples", "samples"),
                   ("leafwise_int8_spread", "spread")])

    if run_mixedbin:
        # the packed path pinned explicitly ON (mixed_bin=true): the gated
        # satellite rate guarding the per-class histogram schedule even if
        # the headline's auto resolution ever changes (scripts/perf_gate.py
        # RATE_KEYS)
        sub_bench("mixedbin",
                  ["--max-bin", str(args.max_bin),
                   "--iters", str(args.iters),
                   "--grow-policy", args.grow_policy,
                   "--hist-dtype", args.hist_dtype,
                   "--mixed-bin", "true"],
                  [("mixedbin_iters_per_sec", "value"),
                   ("mixedbin_vs_cuda", "vs_cuda"),
                   ("mixedbin_spread", "spread")])

    if run_mixedbin and args.tree_learner == "serial":
        # the COMPOSED configuration (ISSUE 12): block-local mixed-bin
        # packing ON the 2-D hybrid mesh, pinned explicitly — the gated
        # mixedbin_hybrid_iters_per_sec lane plus the resolution record
        # perf_gate's absolute mixed-bin check reads (a silent fallback
        # to the uniform layout fails the gate, not just the trajectory)
        sub_bench("mixedbin_hybrid",
                  ["--max-bin", str(args.max_bin),
                   "--iters", str(args.iters),
                   "--grow-policy", args.grow_policy,
                   "--hist-dtype", args.hist_dtype,
                   "--mixed-bin", "true",
                   "--tree-learner", "hybrid"],
                  [("mixedbin_hybrid_iters_per_sec", "value"),
                   ("mixedbin_hybrid_spread", "spread"),
                   ("mixedbin_hybrid_tree_learner", "tree_learner"),
                   ("mixedbin_hybrid_mixed_bin_requested",
                    "mixed_bin_requested"),
                   ("mixedbin_hybrid_mixedbin_expected",
                    "mixedbin_expected"),
                   ("mixedbin_hybrid_mixed_bin_on", "mixed_bin_on")])

    run_predict = not args.skip_parity
    if run_predict:
        # serving lane (ISSUE 7): predictions/sec + p50/p99 latency per
        # batch bucket off the compiled serving engine, the int8-ensemble
        # variant, and the legacy per-tree-scan A/B at 64k.  perf_gate
        # gates predict_b65536/predict_int8_b65536/predict_b1024 rows/sec
        # on the BENCH_r* trajectory next to the training rates.
        sub_bench("predict",
                  ["--bench-predict", "--max-bin", str(args.max_bin),
                   "--iters", str(args.iters)],
                  [(k, k) for k in PREDICT_COPY_KEYS])

    run_serve = not args.skip_parity
    if run_serve:
        # elastic-serving lane (ISSUE 13): p99 + rows/sec under the
        # open-loop load generator through the coalescing front, and the
        # mid-load hot swap's dropped/misscored counts.  perf_gate gates
        # serve_rows_per_sec (rate), serve_p99_us (must-not-grow) and
        # flags ANY nonzero recompile/dropped/misscored absolutely.
        sub_bench("serve",
                  ["--bench-serve", "--max-bin", str(args.max_bin),
                   "--iters", str(args.iters)],
                  [(k, k) for k in SERVE_COPY_KEYS])

    run_ckpt = not args.skip_parity
    if run_ckpt:
        # checkpoint-cost lane (ISSUE 14): ckpt_overhead_pct rides the
        # must-not-grow latency lane and ckpt_restore_exact=False is an
        # ABSOLUTE perf_gate finding (a non-bit-identical same-topology
        # restore must never pass a recorded round unnoticed).
        sub_bench("ckpt",
                  ["--bench-ckpt", "--max-bin", str(args.max_bin),
                   "--iters", str(args.iters),
                   "--grow-policy", args.grow_policy,
                   "--hist-dtype", args.hist_dtype],
                  [(k, k) for k in CKPT_COPY_KEYS])

    run_ingest = not args.skip_parity
    if run_ingest:
        # ingestion lane (ISSUE 8): rows/sec for the chunked
        # parse->bin->HBM pipeline at the headline row count, with the
        # double-buffer A/B and the peak-host-RSS assertion.  perf_gate
        # gates ingest_rows_per_sec on the BENCH_r* trajectory.
        ingest_extra = ["--bench-ingest", "--max-bin", str(args.max_bin),
                        "--iters", "2"]
        if args.ingest_workers > 1:
            # the parallel loader's structural win (selective pass 1)
            # only exists past the 50k-row binning sample, and the
            # worker-pool spawn is a fixed cost — price the workers lane
            # at a data-scale row count.  The sub-bench's own serial
            # lane (ingest_serial_rows_per_sec, same record, same
            # scale) is the matched baseline perf_gate's must-GROW
            # check prefers over cross-round medians.
            ingest_extra += ["--rows", str(max(args.rows, 200_000)),
                             "--ingest-workers", str(args.ingest_workers)]
        sub_bench("ingest", ingest_extra,
                  [(k, k) for k in INGEST_COPY_KEYS])

    if run_maxbin63:
        # the reference's own speed configuration (max_bin=63,
        # include/LightGBM/config.h:137): quarter the one-hot MAC cost at
        # a quality cost measured by scripts/auc_parity.py at 11M x 100
        # (BASELINE.md round-5 addendum: AUC delta -0.0023) — the
        # CUDA-anchor comparison at matched bin budget (VERDICT r4 #2)
        sub_bench("maxbin63",
                  ["--max-bin", "63", "--iters", str(args.iters),
                   "--grow-policy", args.grow_policy,
                   "--hist-dtype", args.hist_dtype],
                  [("maxbin63_iters_per_sec", "value"),
                   ("maxbin63_vs_cuda", "vs_cuda"),
                   ("maxbin63_spread", "spread")])
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
