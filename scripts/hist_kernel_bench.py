"""Microbench histogram-pass formulations on the real TPU.

Variants (all build the same [C, F, B, 3]-shaped level histogram):
  bf16   : current histogram_leafbatch (one-hot x values, bf16 operands)
  int8   : quantized-gradient pass — values stochastically rounded to int8
           per column, one-hot generated int8, int8xint8->int32 MXU matmul,
           dequantized f32 result (modern LightGBM's quantized-training
           idea recast as an MXU matmul)

Usage: python scripts/hist_kernel_bench.py --rows 4000000 --cols 42

``--sweep-classes`` (ISSUE 6) instead runs the bin-width-class sweep: the
same leaf-batched pass at B=63, B=255, and the MIXED per-class schedule
(narrow features at 64 bins + wide at 255 via a PackSpec) so the packing
threshold (io/binning.NARROW_BINS) can be re-derived from measurement when
kernel economics change, instead of folklore.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import histogram_leafbatch
from scripts.tpu_timeit import device_time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=4_000_000)
    p.add_argument("--features", type=int, default=28)
    p.add_argument("--bins", type=int, default=256)
    p.add_argument("--cols", type=int, default=42)
    p.add_argument("--chunk", type=int, default=65536)
    p.add_argument("--variants", default="bf16,int8")
    p.add_argument("--pallas-chunk", type=int, default=2048)
    p.add_argument("--sweep-classes", action="store_true",
                   help="bin-width-class sweep: 63-wide vs 255-wide vs "
                        "the mixed per-class schedule on the same rows "
                        "(re-derives the packing threshold from data)")
    p.add_argument("--narrow-frac", type=float, default=6 / 7,
                   help="fraction of features in the narrow class for "
                        "the mixed lane of --sweep-classes")
    args = p.parse_args()

    rng = np.random.RandomState(0)
    N, F, B, C = args.rows, args.features, args.bins, args.cols

    if args.sweep_classes:
        return sweep_classes(args, rng)
    bins = jnp.asarray(rng.randint(0, B, size=(F, N), dtype=np.int32)
                       .astype(np.int8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32) * 0.3)
    hess = jnp.asarray(rng.rand(N).astype(np.float32) * 0.25)
    col_id = jnp.asarray(rng.randint(0, C, size=N).astype(np.int32))
    col_ok = jnp.asarray(rng.rand(N) < 0.9)

    per_pass_bytes = N * (F + 13)  # bins int8 + g/h f32 + colid i32 + ok
    for v in args.variants.split(","):
        if v == "bf16":
            op = lambda g, h: histogram_leafbatch(
                bins, g, h, col_id, col_ok, C, B, chunk=args.chunk)
        elif v == "int8":
            from lightgbm_tpu.ops.hist_pallas import hist_quant_xla
            op = lambda g, h: hist_quant_xla(
                bins, g, h, col_id, col_ok, C, B, chunk=args.chunk)
        elif v.startswith("pallas"):
            from lightgbm_tpu.ops.hist_pallas import hist_pallas_leafbatch
            dt = "int8" if v.endswith("int8") else "bfloat16"
            ck = args.pallas_chunk
            op = lambda g, h: hist_pallas_leafbatch(
                bins, g, h, col_id, col_ok, C, B, chunk=ck, dtype=dt)
        else:
            raise SystemExit(f"unknown variant {v}")
        t = device_time(op, grad, hess, key_arg=0, reps=(2, 6))
        gbps = per_pass_bytes / t / 1e9
        print(f"{v:6s} rows={N} C={C} chunk={args.chunk}: "
              f"{t*1e3:8.2f} ms/pass  ({gbps:6.1f} GB/s effective)")


def sweep_classes(args, rng):
    """63-wide vs 255-wide vs mixed per-class passes on identical rows.

    The mixed lane builds a real PackSpec (narrow features first, 64-wide
    class; wide features at 255) and calls histogram_leafbatch with it —
    the exact production schedule, so the printed ratio IS the headline
    histogram speedup a dataset with this narrow fraction can expect, and
    the 63-vs-255 lanes bound it from both sides."""
    from lightgbm_tpu.io.binning import PackSpec
    N, F, C = args.rows, args.features, args.cols
    n_narrow = max(1, min(F - 1, int(round(F * args.narrow_frac))))
    grad = jnp.asarray(rng.randn(N).astype(np.float32) * 0.3)
    hess = jnp.asarray(rng.rand(N).astype(np.float32) * 0.25)
    col_id = jnp.asarray(rng.randint(0, C, size=N).astype(np.int32))
    col_ok = jnp.asarray(rng.rand(N) < 0.9)
    per_pass_bytes = N * (F + 13)

    def bins_of(widths):
        return jnp.asarray(np.stack(
            [rng.randint(0, w, size=N) for w in widths]).astype(np.int8))

    lanes = [
        ("b63", bins_of([63] * F), 63, None),
        ("b255", bins_of([255] * F), 255, None),
        ("mixed", bins_of([64] * n_narrow + [255] * (F - n_narrow)), 255,
         PackSpec(widths=(64, 255), counts=(n_narrow, F - n_narrow),
                  perm=tuple(range(F)))),
    ]
    results = {}
    for name, bins, B, spec in lanes:
        op = lambda g, h, _b=bins, _B=B, _s=spec: histogram_leafbatch(
            _b, g, h, col_id, col_ok, C, _B, chunk=args.chunk,
            packing=_s)
        t = device_time(op, grad, hess, key_arg=0, reps=(2, 6))
        results[name] = t
        gbps = per_pass_bytes / t / 1e9
        print(f"{name:6s} rows={N} F={F} C={C}"
              f"{'' if spec is None else ' narrow=%d' % n_narrow}: "
              f"{t*1e3:8.2f} ms/pass  ({gbps:6.1f} GB/s effective)")
    print(f"mixed vs b255 speedup: {results['b255'] / results['mixed']:.2f}x"
          f"  (b63 bound: {results['b255'] / results['b63']:.2f}x)")
    return 0


if __name__ == "__main__":
    main()
