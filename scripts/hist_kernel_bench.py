"""Microbench histogram-pass formulations on the real TPU.

Variants (all build the same [C, F, B, 3]-shaped level histogram):
  bf16   : current histogram_leafbatch (one-hot x values, bf16 operands)
  int8   : quantized-gradient pass — values stochastically rounded to int8
           per column, one-hot generated int8, int8xint8->int32 MXU matmul,
           dequantized f32 result (modern LightGBM's quantized-training
           idea recast as an MXU matmul)

Usage: python scripts/hist_kernel_bench.py --rows 4000000 --cols 42
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import histogram_leafbatch
from scripts.tpu_timeit import device_time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=4_000_000)
    p.add_argument("--features", type=int, default=28)
    p.add_argument("--bins", type=int, default=256)
    p.add_argument("--cols", type=int, default=42)
    p.add_argument("--chunk", type=int, default=65536)
    p.add_argument("--variants", default="bf16,int8")
    p.add_argument("--pallas-chunk", type=int, default=2048)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    N, F, B, C = args.rows, args.features, args.bins, args.cols
    bins = jnp.asarray(rng.randint(0, B, size=(F, N), dtype=np.int32)
                       .astype(np.int8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32) * 0.3)
    hess = jnp.asarray(rng.rand(N).astype(np.float32) * 0.25)
    col_id = jnp.asarray(rng.randint(0, C, size=N).astype(np.int32))
    col_ok = jnp.asarray(rng.rand(N) < 0.9)

    per_pass_bytes = N * (F + 13)  # bins int8 + g/h f32 + colid i32 + ok
    for v in args.variants.split(","):
        if v == "bf16":
            op = lambda g, h: histogram_leafbatch(
                bins, g, h, col_id, col_ok, C, B, chunk=args.chunk)
        elif v == "int8":
            from lightgbm_tpu.ops.hist_pallas import hist_quant_xla
            op = lambda g, h: hist_quant_xla(
                bins, g, h, col_id, col_ok, C, B, chunk=args.chunk)
        elif v.startswith("pallas"):
            from lightgbm_tpu.ops.hist_pallas import hist_pallas_leafbatch
            dt = "int8" if v.endswith("int8") else "bfloat16"
            ck = args.pallas_chunk
            op = lambda g, h: hist_pallas_leafbatch(
                bins, g, h, col_id, col_ok, C, B, chunk=ck, dtype=dt)
        else:
            raise SystemExit(f"unknown variant {v}")
        t = device_time(op, grad, hess, key_arg=0, reps=(2, 6))
        gbps = per_pass_bytes / t / 1e9
        print(f"{v:6s} rows={N} C={C} chunk={args.chunk}: "
              f"{t*1e3:8.2f} ms/pass  ({gbps:6.1f} GB/s effective)")


if __name__ == "__main__":
    main()
