"""Pretty-print a telemetry JSONL file (metrics_out=...) as phase/counter
tables, so BENCH/PROFILE rounds stop hand-assembling them.

Usage:
    python scripts/telemetry_report.py metrics.jsonl
    python scripts/telemetry_report.py --json metrics.jsonl   # machine form

Reads the per-iteration records emitted by lightgbm_tpu/telemetry.py
({"iter", "phase_times", "counters", "eval_metrics", ...} plus an optional
trailing {"summary": true, ...} record) and prints:

  - a per-phase table: total seconds, mean ms/iteration, share of the
    summed phase time (execution spans and trace/compile spans separately),
  - the final kernel-route counter values (cross-host ``allhosts/`` sums
    when the run aggregated them),
  - first/last eval metric values per dataset/metric.
"""
from __future__ import annotations

import argparse
import json
import sys


def load(path: str):
    iters, summary = [], None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("summary"):
                summary = rec
            elif "iter" in rec:
                iters.append(rec)
    return iters, summary


def _sum_phase(iters, key):
    total = {}
    for rec in iters:
        for k, v in rec.get(key, {}).items():
            total[k] = total.get(k, 0.0) + v
    return total


def _table(title, totals, n_iters):
    lines = [title, "-" * len(title)]
    if not totals:
        lines.append("(none recorded)")
        return lines
    grand = sum(totals.values()) or 1.0
    width = max(len(k) for k in totals)
    lines.append(f"{'phase'.ljust(width)}  {'total s':>10}  "
                 f"{'ms/iter':>10}  {'share':>6}")
    for k, v in sorted(totals.items(), key=lambda kv: -kv[1]):
        per = 1000.0 * v / max(n_iters, 1)
        lines.append(f"{k.ljust(width)}  {v:>10.4f}  {per:>10.2f}  "
                     f"{100.0 * v / grand:>5.1f}%")
    return lines


def report(path: str, as_json: bool = False) -> int:
    iters, summary = load(path)
    if not iters and summary is None:
        print(f"no telemetry records in {path}", file=sys.stderr)
        return 1
    n = len(iters)
    exec_totals = _sum_phase(iters, "phase_times")
    trace_totals = _sum_phase(iters, "trace_times")
    counters = (summary or (iters[-1] if iters else {})).get("counters", {})
    evals = {}
    for rec in iters:
        for k, v in rec.get("eval_metrics", {}).items():
            evals.setdefault(k, []).append(v)

    if as_json:
        print(json.dumps({
            "iterations": n,
            "phase_times_total": {k: round(v, 6)
                                  for k, v in sorted(exec_totals.items())},
            "trace_times_total": {k: round(v, 6)
                                  for k, v in sorted(trace_totals.items())},
            "counters": dict(sorted(counters.items())),
            "eval_first_last": {k: [v[0], v[-1]]
                                for k, v in sorted(evals.items())},
        }))
        return 0

    out = [f"telemetry report: {path}  ({n} iteration records"
           + (", summary present)" if summary else ")"), ""]
    out += _table("Execution phases", exec_totals, n)
    out.append("")
    out += _table("Trace/compile attribution", trace_totals, n)
    out.append("")
    out.append("Kernel-route counters")
    out.append("---------------------")
    if counters:
        width = max(len(k) for k in counters)
        for k, v in sorted(counters.items()):
            out.append(f"{k.ljust(width)}  {v}")
    else:
        out.append("(none recorded)")
    if evals:
        out.append("")
        out.append("Eval metrics (first -> last)")
        out.append("----------------------------")
        width = max(len(k) for k in evals)
        for k, v in sorted(evals.items()):
            out.append(f"{k.ljust(width)}  {v[0]} -> {v[-1]}")
    print("\n".join(out))
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", help="telemetry JSONL file (metrics_out=...)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable aggregate instead of tables")
    args = p.parse_args()
    return report(args.path, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
