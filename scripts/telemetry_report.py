"""Pretty-print a telemetry JSONL file (metrics_out=...) as phase/counter
tables, so BENCH/PROFILE rounds stop hand-assembling them.

Usage:
    python scripts/telemetry_report.py metrics.jsonl
    python scripts/telemetry_report.py --json metrics.jsonl   # machine form

Reads the per-iteration records emitted by lightgbm_tpu/telemetry.py
({"iter", "phase_times", "counters", "eval_metrics", ...} plus an optional
trailing {"summary": true, ...} record) and prints:

  - a per-phase table: total seconds, mean ms/iteration, share of the
    summed phase time (execution spans and trace/compile spans separately),
  - the final kernel-route counter values (cross-host ``allhosts/`` sums
    when the run aggregated them),
  - the training-health table (ISSUE 2 ``health`` blocks: NaN/Inf and
    saturation totals, iterations with anomalies, score watermark),
  - the memory table (ISSUE 2 ``memory`` blocks: peak bytes_in_use,
    per-phase byte deltas, the dataset-residency report),
  - the roofline table (ISSUE 4 ``roofline`` block: per-phase static
    flops/bytes joined to measured seconds — attained FLOP/s, HBM GB/s,
    fraction-of-peak when the device kind is known) and the compile
    table (program inventory, compile seconds, cache hits, mid-run
    recompiles),
  - the flight-recorder table (ISSUE 16 ``trace`` block: ring
    occupancy/drop/dump counts, streaming-sketch latency percentiles per
    family, and the per-component serve attribution — mean share and p99
    share of the request wall time),
  - first/last eval metric values per dataset/metric.

``--monitor monitor.jsonl`` additionally renders the live monitor's
windowed snapshot series (ISSUE 20, monitor_out= JSONL): one row per
closed window with the SLO family's delta-sketch count and p50/p99,
the fast/slow burn rates and breach marks.  Works standalone too
(``--monitor`` with no positional path).  The full contract validator
is ``scripts/monitor_report.py --check``; this is the human render
next to the phase tables.

Malformed or truncated JSONL exits with a one-line error (code 2), not a
stack trace — half-written sinks from crashed runs are an expected input.
"""
from __future__ import annotations

import argparse
import json
import sys


class MalformedJSONL(Exception):
    pass


def load(path: str):
    iters, summary, residency = [], None, None
    try:
        f = open(path)
    except OSError as e:
        raise MalformedJSONL(f"cannot read {path}: {e}")
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise MalformedJSONL(
                    f"{path}:{lineno}: malformed JSONL record ({e}) — "
                    "truncated sink from an aborted run?")
            if not isinstance(rec, dict):
                raise MalformedJSONL(
                    f"{path}:{lineno}: record is not a JSON object")
            if rec.get("summary"):
                summary = rec
            elif "iter" in rec:
                iters.append(rec)
            elif "residency" in rec:
                residency = rec["residency"]
    return iters, summary, residency


def _health_totals(iters, summary):
    """Cumulative health keys: prefer the summary's block (exact totals,
    survives partial files), fall back to summing the iteration blocks."""
    if summary and isinstance(summary.get("health"), dict):
        return dict(summary["health"])
    totals = {}
    for rec in iters:
        for k, v in (rec.get("health") or {}).items():
            if k == "eval_divergence":
                totals["eval_divergence_events"] = (
                    totals.get("eval_divergence_events", 0) + len(v))
            elif k == "score_max_abs":
                totals[k] = max(totals.get(k, 0.0), v)
            else:
                totals[k] = totals.get(k, 0) + v
    return totals


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return ("%.1f %s" % (n, unit)) if unit != "B" else "%d B" % n
        n /= 1024.0
    return "%d" % n


def _sum_phase(iters, key):
    total = {}
    for rec in iters:
        for k, v in rec.get(key, {}).items():
            total[k] = total.get(k, 0.0) + v
    return total


def _table(title, totals, n_iters):
    lines = [title, "-" * len(title)]
    if not totals:
        lines.append("(none recorded)")
        return lines
    grand = sum(totals.values()) or 1.0
    width = max(len(k) for k in totals)
    lines.append(f"{'phase'.ljust(width)}  {'total s':>10}  "
                 f"{'ms/iter':>10}  {'share':>6}")
    for k, v in sorted(totals.items(), key=lambda kv: -kv[1]):
        per = 1000.0 * v / max(n_iters, 1)
        lines.append(f"{k.ljust(width)}  {v:>10.4f}  {per:>10.2f}  "
                     f"{100.0 * v / grand:>5.1f}%")
    return lines


def _roofline_lines(roofline):
    out = ["Roofline (static costs x measured spans)",
           "---------------------------------------"]
    if not roofline:
        out.append("(no roofline block — emitted by metrics_out= runs "
                   "since ISSUE 4)")
        return out
    peaks = roofline.get("peaks")
    out.append("device_kind: %s   peaks: %s"
               % (roofline.get("device_kind", "?"),
                  ("unavailable" if peaks in (None, "unavailable")
                   else ", ".join("%s=%.3g" % kv
                                  for kv in sorted(peaks.items())))))
    phases = roofline.get("phases") or {}
    if phases:
        width = max(len(k) for k in phases)
        out.append(f"{'phase'.ljust(width)}  {'GFLOP':>10}  {'GB':>8}  "
                   f"{'sec':>8}  {'GFLOP/s':>9}  {'GB/s':>7}  "
                   f"{'%peak':>6}  {'AI':>7}")
        for k, b in sorted(phases.items()):
            frac = b.get("frac_of_peak_flops")
            out.append(
                f"{k.ljust(width)}  {b.get('flops', 0) / 1e9:>10.3f}  "
                f"{b.get('bytes_accessed', 0) / 1e9:>8.3f}  "
                f"{b.get('seconds', 0):>8.3f}  "
                + ("%9.2f" % (b["attained_flops_per_sec"] / 1e9)
                   if "attained_flops_per_sec" in b else "%9s" % "-") + "  "
                + ("%7.2f" % b["attained_hbm_gbps"]
                   if "attained_hbm_gbps" in b else "%7s" % "-") + "  "
                + ("%5.1f%%" % (100 * frac) if frac is not None
                   else "%6s" % "-") + "  "
                + ("%7.3f" % b["arithmetic_intensity"]
                   if "arithmetic_intensity" in b else "%7s" % "-"))
    else:
        out.append("(no phases captured)")
    passes = roofline.get("traced_passes") or []
    if passes:
        out.append("analytic traced passes (Pallas/custom-call costs XLA "
                   "analysis cannot see):")
        for n in passes:
            out.append("  %-10s %-42s traces=%-3d TMAC/pass=%.4g"
                       % (n.get("phase", "?"), str(n.get("key")),
                          n.get("traces", 0), n.get("macs", 0.0) / 1e12))
    return out


def _interconnect_lines(ic):
    """Per-collective-site wire-metrics table (ISSUE 5 ``interconnect``
    block): logical payload bytes and attained GB/s per site/phase."""
    out = ["Interconnect (per-collective wire metrics)",
           "------------------------------------------"]
    if not ic or not ic.get("sites"):
        out.append("(no interconnect block — emitted by multi-device "
                   "runs with collective seams traced)")
        return out
    sites = ic["sites"]
    width = max(len(s) for s in sites)
    out.append(f"{'site'.ljust(width)}  {'kind':>12}  {'bytes/call':>12}  "
               f"{'est calls':>9}  {'est bytes':>12}  {'GB/s':>10}")
    for name, blk in sorted(sites.items(),
                            key=lambda kv: -kv[1].get("est_bytes", 0)):
        rate = blk.get("attained_gb_per_s")
        out.append(
            f"{name.ljust(width)}  {blk.get('kind', '?'):>12}  "
            f"{_fmt_bytes(blk.get('bytes_per_call', 0)):>12}  "
            f"{blk.get('est_calls', 0):>9}  "
            f"{_fmt_bytes(blk.get('est_bytes', 0)):>12}  "
            + (f"{rate:>10.4f}" if isinstance(rate, (int, float))
               else f"{'-':>10}"))
    for phase, blk in sorted((ic.get("phases") or {}).items()):
        rate = blk.get("attained_gb_per_s")
        out.append("phase %-12s  %s over %.4fs span -> %s GB/s"
                   % (phase, _fmt_bytes(blk.get("est_bytes", 0)),
                      blk.get("span_seconds", 0.0),
                      ("%.4f" % rate) if isinstance(rate, (int, float))
                      else "-"))
    if ic.get("note"):
        out.append("note: %s" % ic["note"])
    return out


def _ingest_lines(counters, summary_phase_times):
    """The ``ingest/*`` counter family (ISSUE 8, io/streaming.py) with
    derived H2D GB/s: payload bytes over the host time actually blocked
    on transfers, and over the whole ingest span (effective rate).  The
    overlap-hidden estimate is the double buffer's measured win."""
    out = ["Streaming ingestion (ingest/*)",
           "------------------------------"]
    fam = {k: v for k, v in counters.items() if k.startswith("ingest/")}
    if not fam:
        out.append("(no ingest counters — resident load, or telemetry "
                   "was off during ingestion)")
        return out
    width = max(len(k) for k in fam)
    for k, v in sorted(fam.items()):
        val = _fmt_bytes(v) if k.endswith("_bytes") else str(v)
        out.append(f"{k.ljust(width)}  {val}")
    h2d = fam.get("ingest/h2d_bytes", 0)
    wait_s = fam.get("ingest/h2d_wait_us", 0) / 1e6
    hidden_s = fam.get("ingest/overlap_hidden_us", 0) / 1e6
    span_s = (summary_phase_times or {}).get("ingest", 0.0)
    if h2d and wait_s > 0:
        out.append("H2D attained (blocked time)  %.2f GB/s"
                   % (h2d / wait_s / 1e9))
    if h2d and span_s > 0:
        out.append("H2D effective (ingest span)  %.2f GB/s  over %.2f s"
                   % (h2d / span_s / 1e9, span_s))
    if hidden_s > 0:
        out.append("overlap-hidden transfer time  %.2f s" % hidden_s)
    return out


def _serve_lines(counters):
    """The ``serve/*`` counter family (ISSUE 7 engine + ISSUE 13 front)
    with derived coalescing/linger/queue means.  The coalesced batch
    SIZE histogram is the ``serve/bucket_<B>`` rows; the tree-sharded
    wire bytes ride the interconnect block (sites ``serve/tree_*``)."""
    out = ["Serving (serve/*)", "-----------------"]
    fam = {k: v for k, v in counters.items() if k.startswith("serve/")}
    if not fam:
        out.append("(no serve counters — no engine/front activity while "
                   "telemetry was armed)")
        return out
    width = max(len(k) for k in fam)
    for k, v in sorted(fam.items()):
        out.append(f"{k.ljust(width)}  {v}")
    batches = fam.get("serve/coalesced_batches", 0)
    if batches:
        out.append("mean coalesced batch  %.1f rows over %.1f requests"
                   % (fam.get("serve/coalesced_rows", 0) / batches,
                      fam.get("serve/coalesced_requests", 0) / batches))
        out.append("mean linger wait      %.0f us"
                   % (fam.get("serve/linger_wait_us", 0) / batches))
    samples = fam.get("serve/queue_depth_samples", 0)
    if samples:
        # queue_peak_rows is a cumulative counter each front's close()
        # adds its own peak into — a SUM across fronts, not a job peak
        out.append("mean queue depth      %.1f rows "
                   "(per-front peaks summed: %d)"
                   % (fam.get("serve/queue_depth_rows", 0) / samples,
                      fam.get("serve/queue_peak_rows", 0)))
    swaps = fam.get("serve/swaps", 0)
    if swaps:
        out.append("mean swap drain       %.0f us over %d swap(s)"
                   % (fam.get("serve/swap_drain_us", 0) / swaps, swaps))
    return out


def _trace_lines(trace):
    """The flight-recorder block (ISSUE 16, ``trace`` summary key from
    tracing.snapshot()): ring occupancy + exact drop count, per-family
    streaming-sketch percentiles, and the per-component serve-latency
    attribution table.  Component means/p99s come from the same
    fixed-memory log-bucket sketches, so shares are exact to within the
    sketch's bucket resolution."""
    out = ["Flight recorder (trace)", "-----------------------"]
    if not trace:
        out.append("(no trace block — the recorder arms with any "
                   "metrics_out= session; see lightgbm_tpu/tracing.py)")
        return out
    out.append("ring %d/%d events  (appended %d, dropped %d, dumps %d, "
               "sketch growth %g%s)"
               % (trace.get("events", 0), trace.get("ring_events", 0),
                  trace.get("appended", 0), trace.get("dropped", 0),
                  trace.get("dumps", 0), trace.get("sketch_growth", 0.0),
                  ", default ring" if trace.get("default_ring") else ""))
    sketches = trace.get("sketches") or {}
    if not sketches:
        out.append("(no sketch observations)")
        return out

    def _us(x):
        return ("%10.1f" % x) if isinstance(x, (int, float)) else "%10s" % "-"

    width = max(len(k) for k in sketches)
    out.append(f"{'family'.ljust(width)}  {'count':>8}  {'mean us':>10}  "
               f"{'p50 us':>10}  {'p99 us':>10}  {'p999 us':>10}")
    for fam, pc in sorted(sketches.items()):
        out.append(f"{fam.ljust(width)}  {pc.get('count', 0):>8}  "
                   + "  ".join(_us(pc.get(k))
                               for k in ("mean", "p50", "p99", "p999")))
    # per-component serve attribution: where a request's wall time went
    # (component order mirrors tracing.COMPONENTS — the timeline order)
    wall = sketches.get("serve_wall_us") or {}
    comps = [(c, sketches.get("serve_%s_us" % c))
             for c in ("queue", "linger", "coalesce", "dispatch", "walk",
                       "scatter")]
    comps = [(c, pc) for c, pc in comps if pc]
    if wall and comps:
        mean_total = sum(pc.get("mean") or 0.0 for _c, pc in comps)
        wall_p99 = wall.get("p99") or 0.0
        out.append("serve attribution (per component of the exact "
                   "wall-time identity):")
        for c, pc in comps:
            mean = pc.get("mean") or 0.0
            p99 = pc.get("p99") or 0.0
            out.append("  %-9s mean %9.1f us (%5.1f%%)   p99 %9.1f us "
                       "(%5.1f%% of wall p99)"
                       % (c, mean,
                          100.0 * mean / mean_total if mean_total else 0.0,
                          p99,
                          100.0 * p99 / wall_p99 if wall_p99 else 0.0))
    return out


def _compile_lines(comp):
    out = ["Compile observability", "---------------------"]
    if not comp:
        out.append("(no compile block — emitted by metrics_out= runs "
                   "since ISSUE 4)")
        return out
    out.append("programs captured  %d  (cold compile %.2f s, %d warm)"
               % (comp.get("program_count", 0),
                  comp.get("total_compile_seconds", 0.0),
                  comp.get("warm_programs", 0)))
    out.append("backend compiles   %d   persistent-cache hits %d   "
               "MID-RUN recompiles %d%s"
               % (comp.get("backend_compiles", 0),
                  comp.get("persistent_cache_hits", 0),
                  comp.get("midrun_recompiles", 0),
                  "  <-- cache-key leak?"
                  if comp.get("midrun_recompiles", 0) else ""))
    progs = comp.get("programs") or []
    if progs:
        width = max(len(p.get("name", "?")) for p in progs)
        out.append(f"{'program'.ljust(width)}  {'compile s':>9}  "
                   f"{'calls':>5}  {'GFLOP':>9}  {'MB acc':>8}")
        for p in progs:
            fl = p.get("flops")
            by = p.get("bytes_accessed")
            out.append(
                f"{p.get('name', '?').ljust(width)}  "
                f"{p.get('compile_seconds', 0.0):>9.2f}  "
                f"{p.get('calls', 0):>5d}  "
                + ("%9.3f" % (fl / 1e9) if fl is not None
                   else "%9s" % "-") + "  "
                + ("%8.1f" % (by / 1e6) if by is not None
                   else "%8s" % "-")
                + ("  [warm]" if p.get("warm") else "")
                + ("  [%s]" % p["error"] if p.get("error") else ""))
    return out


def report(path: str, as_json: bool = False) -> int:
    try:
        iters, summary, residency = load(path)
    except MalformedJSONL as e:
        print(f"telemetry_report error: {e}", file=sys.stderr)
        return 2
    if not iters and summary is None:
        print(f"no telemetry records in {path}", file=sys.stderr)
        return 1
    n = len(iters)
    exec_totals = _sum_phase(iters, "phase_times")
    trace_totals = _sum_phase(iters, "trace_times")
    counters = (summary or (iters[-1] if iters else {})).get("counters", {})
    health = _health_totals(iters, summary)
    mem = (summary or {}).get("memory") or (
        iters[-1].get("memory") if iters else None) or {}
    if residency is None:
        residency = mem.get("residency")
    evals = {}
    for rec in iters:
        for k, v in rec.get("eval_metrics", {}).items():
            evals.setdefault(k, []).append(v)

    roofline = (summary or {}).get("roofline")
    comp = (summary or {}).get("compile")
    interconnect = (summary or {}).get("interconnect")
    trace = (summary or {}).get("trace")

    if as_json:
        print(json.dumps({
            "iterations": n,
            "phase_times_total": {k: round(v, 6)
                                  for k, v in sorted(exec_totals.items())},
            "trace_times_total": {k: round(v, 6)
                                  for k, v in sorted(trace_totals.items())},
            "counters": dict(sorted(counters.items())),
            "health": dict(sorted(health.items())),
            "memory": mem,
            "residency": residency or {},
            "roofline": roofline or {},
            "compile": comp or {},
            "interconnect": interconnect or {},
            "trace": trace or {},
            "eval_first_last": {k: [v[0], v[-1]]
                                for k, v in sorted(evals.items())},
        }))
        return 0

    out = [f"telemetry report: {path}  ({n} iteration records"
           + (", summary present)" if summary else ")"), ""]
    out += _table("Execution phases", exec_totals, n)
    out.append("")
    out += _table("Trace/compile attribution", trace_totals, n)
    out.append("")
    out.append("Kernel-route counters")
    out.append("---------------------")
    if counters:
        width = max(len(k) for k in counters)
        for k, v in sorted(counters.items()):
            out.append(f"{k.ljust(width)}  {v}")
    else:
        out.append("(none recorded)")

    out.append("")
    out.append("Training health (totals)")
    out.append("------------------------")
    if health:
        width = max(len(k) for k in health)
        for k, v in sorted(health.items()):
            val = ("%.6g" % v if isinstance(v, float) else str(v))
            out.append(f"{k.ljust(width)}  {val}")
    else:
        out.append("(no health blocks — train with health=true or "
                   "metrics_out=)")

    out.append("")
    out.append("Memory")
    out.append("------")
    if mem:
        out.append("peak bytes_in_use  %s  (source: %s)"
                   % (_fmt_bytes(mem.get("peak_bytes_in_use", 0)),
                      mem.get("source", "?")))
        if "allhosts_peak_bytes_in_use" in mem:
            out.append("all-hosts peak     %s"
                       % _fmt_bytes(mem["allhosts_peak_bytes_in_use"]))
        deltas = mem.get("phase_delta_bytes", {})
        if deltas:
            width = max(len(k) for k in deltas)
            out.append("per-phase cumulative byte deltas:")
            for k, v in sorted(deltas.items(), key=lambda kv: -abs(kv[1])):
                out.append(f"  {k.ljust(width)}  {_fmt_bytes(v):>12}")
    else:
        out.append("(no memory blocks — train with memory_stats=true or "
                   "metrics_out=)")
    if residency:
        out.append("dataset residency:")
        width = max(len(k) for k in residency)
        for k, v in residency.items():
            val = _fmt_bytes(v) if k.endswith("_bytes") else str(v)
            out.append(f"  {k.ljust(width)}  {val:>12}")
    out.append("")
    out += _ingest_lines(counters, (summary or {}).get("phase_times"))
    out.append("")
    out += _serve_lines(counters)
    out.append("")
    out += _roofline_lines(roofline)
    out.append("")
    out += _interconnect_lines(interconnect)
    out.append("")
    out += _trace_lines(trace)
    out.append("")
    out += _compile_lines(comp)
    if evals:
        out.append("")
        out.append("Eval metrics (first -> last)")
        out.append("----------------------------")
        width = max(len(k) for k in evals)
        for k, v in sorted(evals.items()):
            out.append(f"{k.ljust(width)}  {v[0]} -> {v[-1]}")
    print("\n".join(out))
    return 0


def _monitor_lines(path):
    """The live monitor's windowed snapshot series (ISSUE 20,
    ``monitor_out=`` JSONL): per-window SLO-family delta-sketch count
    and p50/p99, burn rates and breach marks.  Percentiles come from
    the emitted window sketches — exact per-bucket deltas of the
    recorder's cumulative sketches, same resolution contract."""
    import math

    def _quantile(sk, q):
        zero = int(sk.get("zero", 0))
        buckets = {int(i): int(c)
                   for i, c in (sk.get("buckets") or {}).items()}
        total = zero + sum(buckets.values())
        if total == 0:
            return None
        rank = min(total - 1, max(0, int(math.ceil(q * total)) - 1))
        if rank < zero:
            return 0.0
        g, seen = float(sk.get("growth", 1.05)), zero
        for i in sorted(buckets):
            seen += buckets[i]
            if rank < seen:
                return g ** (i + 0.5)
        return None

    try:
        f = open(path)
    except OSError as e:
        raise MalformedJSONL(f"cannot read {path}: {e}")
    header, windows, close = None, [], None
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise MalformedJSONL(f"{path}:{lineno}: bad JSONL ({e})")
            if isinstance(rec, dict) and "monitor_header" in rec:
                header = rec["monitor_header"]
            elif isinstance(rec, dict) and "monitor_window" in rec:
                windows.append(rec["monitor_window"])
            elif isinstance(rec, dict) and "monitor_close" in rec:
                close = rec["monitor_close"]
    if header is None:
        raise MalformedJSONL(f"{path}: no monitor_header line")
    slo = header.get("slo")
    fam = (slo or {}).get("family") or "serve_wall_us"
    out = ["Live monitor (windowed, %s)" % fam,
           "-" * (25 + len(fam)),
           "interval %ss  %d window(s)%s"
           % (header.get("interval_s"), len(windows),
              "  slo p99<=%gus/%gs" % (slo["p99_us"], slo["window_s"])
              if slo else "")]

    def _us(x):
        return ("%9.1f" % x) if isinstance(x, (int, float)) else "%9s" % "-"

    out.append("%6s  %7s  %9s  %9s  %8s  %8s  %s"
               % ("window", "count", "p50 us", "p99 us", "fast", "slow",
                  "breach"))
    for w in windows:
        sk = (w.get("sketches") or {}).get(fam)
        ws = w.get("slo") or {}
        out.append("%6s  %7d  %s  %s  %8s  %8s  %s"
                   % (w.get("window"),
                      0 if sk is None else (
                          int(sk.get("zero", 0))
                          + sum(int(c) for c in
                                (sk.get("buckets") or {}).values())),
                      _us(None if sk is None else _quantile(sk, 0.50)),
                      _us(None if sk is None else _quantile(sk, 0.99)),
                      ("%.3f" % ws["fast_burn"])
                      if isinstance(ws.get("fast_burn"),
                                    (int, float)) else "-",
                      ("%.3f" % ws["slow_burn"])
                      if isinstance(ws.get("slow_burn"),
                                    (int, float)) else "-",
                      "BREACH" if ws.get("breach") else ""))
    if not windows:
        out.append("(no closed windows)")
    if close is not None:
        out.append("close: reason=%s windows=%s breaches=%s"
                   % (close.get("reason"), close.get("windows"),
                      close.get("breaches")))
        for key, d in sorted((close.get("drift") or {}).items()):
            out.append("  drift %s: n=%s psi=%s drift=%s aa_psi=%s"
                       % (key, d.get("n"),
                          "-" if d.get("psi") is None
                          else "%.4f" % d["psi"], d.get("drift"),
                          "-" if d.get("aa_psi") is None
                          else "%.4f" % d["aa_psi"]))
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", nargs="?", default=None,
                   help="telemetry JSONL file (metrics_out=...)")
    p.add_argument("--monitor", metavar="JSONL", default=None,
                   help="also render a live-monitor windowed series "
                        "(monitor_out= JSONL, ISSUE 20)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable aggregate instead of tables")
    args = p.parse_args()
    if args.path is None and args.monitor is None:
        p.error("need a telemetry JSONL path and/or --monitor")
    rc = 0
    if args.path is not None:
        rc = report(args.path, as_json=args.json)
    if args.monitor is not None:
        try:
            print("\n".join(_monitor_lines(args.monitor)))
        except MalformedJSONL as e:
            print(f"telemetry_report error: {e}", file=sys.stderr)
            return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
