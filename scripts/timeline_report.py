"""Merge per-process telemetry shards into one job timeline + skew report.

Input: the JSONL shards timeline mode writes (``telemetry.set_timeline`` /
the ``timeline=`` config option) — ``<metrics_out>.shard-<i>of<n>.jsonl``,
each headed by a ``shard`` record carrying the writer's host fingerprint
and the clock offset measured by ``parallel/mesh.clock_handshake`` at
setup.  Every iteration/summary record carries a LOCAL wall-clock ``t``;
the merge maps each shard's stamps onto the leader's clock
(``t + clock_offset_s``) before ordering, so cross-host event order
survives deliberately skewed clocks (tested).

Outputs:

- an ordered job timeline (one line per record, leader-clock time,
  host-tagged),
- a per-phase SKEW table: for each canonical phase, the cross-host
  dispersion of per-iteration compute time — ``skew = max/median`` per
  iteration, reported as the per-phase maximum and mean — plus a
  barrier-wait estimate per host (``max_host_iter_time - own``: time a
  host spends waiting for the slowest peer inside the collectives) and,
  when the summary carries an ``interconnect`` block, the wire-time
  decomposition (estimated bytes at the attained GB/s),
- a PERSISTENT-STRAGGLER flag: one host slowest ≥ K consecutive
  iterations (``--straggler-k``, default 3) is a host problem, not noise
  — a slow wire slows everyone, a slow host shows up here,
- ``--perfetto out.json``: a Chrome/Perfetto trace (one track per
  process, one slice per phase per iteration) for eyeball debugging.

Crash tolerance: a shard whose writer was killed mid-write ends in one
truncated line — skipped with a note, never a crash (the sink flushes
per record, so at most the LAST line of a shard can be partial; a
malformed line anywhere else is reported as corruption).

Usage::

    python scripts/timeline_report.py run.jsonl.shard-*.jsonl
    python scripts/timeline_report.py --glob 'run.jsonl.shard-*' \
        --perfetto trace.json

Exit codes: 0 = report printed, 1 = persistent straggler flagged,
2 = unreadable/malformed input.
"""
from __future__ import annotations

import argparse
import glob as globmod
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# the skew/straggler logic is SHARED with the trainer's live mesh-shrink
# policy (ISSUE 14): one implementation, lightgbm_tpu/elastic.py — this
# script merges shards into the row shape and delegates.  Importing the
# package may initialize jax; keep it on the CPU platform like the other
# analysis scripts.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from lightgbm_tpu import elastic  # noqa: E402

CANONICAL_PHASES = elastic.CANONICAL_PHASES


class ReportError(Exception):
    """Malformed input (exit code 2)."""


def load_shard(path: str) -> dict:
    """One shard -> {path, header, records, truncated}.

    The FINAL line may be truncated (killed writer); anything malformed
    before it is corruption and raises."""
    records: List[dict] = []
    truncated = False
    try:
        with open(path) as f:
            lines = f.read().split("\n")
    except OSError as e:
        raise ReportError(f"{path}: unreadable ({e})")
    # drop the artifact of the trailing newline
    if lines and lines[-1] == "":
        lines.pop()
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            if i == len(lines) - 1:
                truncated = True   # killed mid-write: expected, skip
                break
            raise ReportError(
                f"{path}:{i + 1}: malformed JSONL mid-file (corruption, "
                "not a crash tail)")
        records.append(rec)
    header = None
    if records and "shard" in records[0]:
        header = records[0]["shard"]
        records = records[1:]
    return {"path": path, "header": header or {}, "records": records,
            "truncated": truncated}


def shard_label(shard: dict) -> str:
    h = shard["header"]
    if "process_index" in h:
        label = "p%d" % h["process_index"]
        if h.get("host") and h["host"] != "unknown":
            label += "@" + str(h["host"])
        return label
    return shard["path"].rsplit("/", 1)[-1]


def merge_timeline(shards: List[dict]) -> List[dict]:
    """All records on the LEADER's clock, time-ordered.  Each event gains
    ``_host`` (shard label) and ``_t`` (leader-clock stamp; records
    without a local ``t`` sort by arrival order at the end)."""
    events = []
    for order, shard in enumerate(shards):
        off = float(shard["header"].get("clock_offset_s", 0.0))
        label = shard_label(shard)
        for seq, rec in enumerate(shard["records"]):
            ev = dict(rec)
            ev["_host"] = label
            ev["_seq"] = (order, seq)
            if isinstance(rec.get("t"), (int, float)):
                ev["_t"] = float(rec["t"]) + off
            events.append(ev)
    stamped = [e for e in events if "_t" in e]
    loose = [e for e in events if "_t" not in e]
    stamped.sort(key=lambda e: (e["_t"], e["_seq"]))
    return stamped + loose


def _phase_rows(shards: List[dict]) -> Dict[int, Dict[str, Dict[str, float]]]:
    """{iteration: {host: {phase: seconds}}} from the iteration records."""
    rows: Dict[int, Dict[str, Dict[str, float]]] = {}
    for shard in shards:
        label = shard_label(shard)
        for rec in shard["records"]:
            if "iter" not in rec or "phase_times" not in rec:
                continue
            rows.setdefault(int(rec["iter"]), {})[label] = {
                k: float(v) for k, v in rec["phase_times"].items()}
    return rows


def skew_report(shards: List[dict], straggler_k: int = 3) -> dict:
    """Per-phase cross-host skew + barrier-wait decomposition + the
    persistent-straggler flag.  Needs ≥2 shards with overlapping
    iteration records; degrades to an empty report otherwise.  The
    computation itself is ``lightgbm_tpu.elastic.skew_from_rows`` — the
    SAME implementation the trainer's live mesh-shrink policy consumes,
    so post-mortem and live verdicts can never diverge."""
    out = elastic.skew_from_rows(_phase_rows(shards),
                                 straggler_k=straggler_k)
    wire = _wire_decomposition(shards)
    if wire:
        out["wire"] = wire
    return out


def _wire_decomposition(shards: List[dict]) -> Optional[dict]:
    """Barrier-wait vs wire-time: the interconnect block's estimated
    bytes at the attained aggregate rate give the floor wire seconds;
    barrier wait (skew_report) is everything above it."""
    for shard in shards:
        for rec in reversed(shard["records"]):
            ic = rec.get("interconnect")
            if not isinstance(ic, dict):
                continue
            total_bytes = sum(b.get("est_bytes", 0)
                              for b in ic.get("phases", {}).values())
            secs = sum(b.get("span_seconds") or 0.0
                       for b in ic.get("phases", {}).values())
            return {
                "est_bytes_total": int(total_bytes),
                "collective_span_s": round(secs, 6),
                "attained_gb_per_s": (round(total_bytes / secs / 1e9, 6)
                                      if secs > 0 else None),
                "host": shard_label(shard),
            }
    return None


def perfetto_trace(shards: List[dict]) -> List[dict]:
    """Chrome-trace events: one pid per shard, one complete slice ("X")
    per phase per iteration.  Phase slices are laid out back-to-back
    ENDING at the record's leader-clock stamp (the record is written at
    iteration end); start times inside an iteration are therefore
    approximate, durations and cross-host alignment exact."""
    events = []
    for pid, shard in enumerate(shards):
        off = float(shard["header"].get("clock_offset_s", 0.0))
        label = shard_label(shard)
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": label}})
        for rec in shard["records"]:
            if "iter" not in rec or "phase_times" not in rec \
                    or not isinstance(rec.get("t"), (int, float)):
                continue
            end_us = (float(rec["t"]) + off) * 1e6
            cursor = end_us - sum(v for v in rec["phase_times"].values()) \
                * 1e6
            for phase in sorted(rec["phase_times"]):
                dur = float(rec["phase_times"][phase]) * 1e6
                if dur <= 0:
                    continue
                events.append({
                    "ph": "X", "pid": pid, "tid": 0,
                    "name": phase, "ts": round(cursor, 1),
                    "dur": round(dur, 1),
                    "args": {"iter": rec["iter"]},
                })
                cursor += dur
    return events


def render(shards: List[dict], skew: dict, timeline_rows: int = 40) -> str:
    lines = []
    lines.append("shards: %d" % len(shards))
    for shard in shards:
        h = shard["header"]
        note = " [truncated tail]" if shard["truncated"] else ""
        lines.append("  %-16s offset=%+.6fs records=%d%s"
                     % (shard_label(shard),
                        float(h.get("clock_offset_s", 0.0)),
                        len(shard["records"]), note))
    events = merge_timeline(shards)
    stamped = [e for e in events if "_t" in e]
    if stamped:
        t0 = stamped[0]["_t"]
        lines.append("")
        lines.append("timeline (leader clock, first %d of %d records):"
                     % (min(timeline_rows, len(stamped)), len(stamped)))
        for ev in stamped[:timeline_rows]:
            what = ("iter %s" % ev["iter"] if "iter" in ev
                    else "summary" if ev.get("summary")
                    else "/".join(sorted(set(ev)
                                         - {"_host", "_seq", "_t", "t"})))
            lines.append("  +%8.3fs  %-16s %s"
                         % (ev["_t"] - t0, ev["_host"], what))
    lines.append("")
    lines.append("per-phase cross-host skew (%d iterations, %d hosts):"
                 % (skew["iterations_compared"], len(skew["hosts"])))
    if skew["phases"]:
        lines.append("  %-12s %10s %10s %6s"
                     % ("phase", "max_skew", "mean_skew", "iters"))
        for p, blk in sorted(skew["phases"].items()):
            lines.append("  %-12s %10.3f %10.3f %6d"
                         % (p, blk["max_skew"], blk["mean_skew"],
                            blk["iterations"]))
    else:
        lines.append("  (need >= 2 shards with overlapping iteration "
                     "records)")
    if skew["barrier_wait_s"]:
        lines.append("barrier wait (s idle at collectives, per host):")
        for h, v in skew["barrier_wait_s"].items():
            lines.append("  %-16s %10.4f" % (h, v))
    if skew.get("wire"):
        w = skew["wire"]
        lines.append("wire estimate: %d bytes over %.4fs collective span"
                     " -> %s GB/s attained"
                     % (w["est_bytes_total"], w["collective_span_s"],
                        w["attained_gb_per_s"]))
    if skew["persistent_straggler"]:
        lines.append("PERSISTENT STRAGGLER: %s slowest >= %d consecutive "
                     "iterations" % (skew["persistent_straggler"],
                                     skew["straggler_k"]))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("shards", nargs="*", help="shard JSONL paths")
    p.add_argument("--glob", action="append", default=[],
                   help="shard path glob(s), e.g. 'run.jsonl.shard-*'")
    p.add_argument("--straggler-k", type=int, default=3,
                   help="consecutive slowest-host iterations that flag a "
                        "persistent straggler (default %(default)s)")
    p.add_argument("--perfetto", metavar="OUT.json",
                   help="write a Chrome/Perfetto trace JSON")
    p.add_argument("--json", action="store_true",
                   help="machine-readable skew report")
    args = p.parse_args(argv)
    paths = sorted(set(args.shards)
                   | {f for g in args.glob for f in globmod.glob(g)})
    if not paths:
        print("timeline_report error: no shard files", file=sys.stderr)
        return 2
    try:
        shards = [load_shard(pth) for pth in paths]
    except ReportError as e:
        print(f"timeline_report error: {e}", file=sys.stderr)
        return 2
    skew = skew_report(shards, straggler_k=args.straggler_k)
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump({"traceEvents": perfetto_trace(shards)}, f)
    if args.json:
        print(json.dumps(skew))
    else:
        print(render(shards, skew))
    return 1 if skew["persistent_straggler"] else 0


if __name__ == "__main__":
    sys.exit(main())
