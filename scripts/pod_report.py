"""Merge + render pod-scope flight-recorder dumps (ISSUE 17).

Usage:
    python scripts/pod_report.py trace-*.jsonl
    python scripts/pod_report.py --json  trace-*.jsonl
    python scripts/pod_report.py --check trace-*.jsonl
    python scripts/pod_report.py --wire MULTICHIP_r07.json trace-*.jsonl

Takes the per-host dumps one run's processes flushed (tracing.py; one
``trace_header`` line carrying host/process/run identity, then ring
events) and produces the pod view lightgbm_tpu/podtrace.py computes:

  - clock alignment: per-host offset onto the reference host's clock,
    WITH its collective-duration error bound (matched pod-wide
    ``collective_sync`` events; the bound is part of the answer);
  - the merged global timeline (order-independent, event-conserving)
    and pod-wide latency sketch percentiles (associative bucket merge);
  - the per-seam roofline table: measured collective span seconds
    joined against the dumps' wire byte model, attained GB/s and the
    fraction of the chip's interconnect peak (None off-TPU — honest);
  - per-host compute vs collective-wait per iteration, and the skew /
    persistent-straggler verdict via ``elastic.skew_from_rows`` — the
    SAME rule the live StragglerTracker applies, so post-mortem and
    live verdicts cannot drift;
  - per-host ingest attribution: tokenizer vs bin vs H2D percentages.

``--check`` exits 1 on any violated contract: header bookkeeping drift
or mixed run ids, a host whose clock cannot be aligned or whose
alignment estimates disagree beyond their recorded bounds, a merged
timeline that drops/invents events or breaks any per-request
sum(components)==wall identity, or a measured seam missing from the
byte model (byte-model drift).  Exits 2 on unreadable input.

``--wire`` merges extra per-site bytes into the model (a
MULTICHIP_WIRE ``{"sites": {"data": {site: bytes}}}`` record, an
interconnect snapshot, or a plain ``{site: bytes}`` map) so the
roofline covers every site the wire smoke prices.

Needs only this repo + numpy (the skew rule imports
lightgbm_tpu.elastic; JAX stays uninitialized on CPU).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu import costmodel, elastic, podtrace, tracing  # noqa: E402


def _load_wire_arg(path: str) -> dict:
    """Extra byte-model sites from a --wire file: accepts a plain
    {site: bytes} map, an interconnect snapshot ({"sites": {site:
    {est_bytes...}}}) or a MULTICHIP_WIRE record ({"sites": {"data":
    {site: bytes}, ...}} — every schema's map unions in)."""
    with open(path) as f:
        rec = json.load(f)
    sites = rec.get("sites", rec) if isinstance(rec, dict) else {}
    out = {}
    for k, v in sites.items():
        if isinstance(v, dict) and "est_bytes" not in v:
            for site, b in v.items():       # MULTICHIP_WIRE per-schema
                cur = out.get(site)
                if cur is None or int(b) > int(cur.get("est_bytes", 0)):
                    out[site] = {"est_bytes": int(b)}
        elif isinstance(v, dict):
            out[k] = v
        else:
            out[k] = {"est_bytes": int(v)}
    return out


def build_report(dumps, extra_sites=None, device_kind=None,
                 straggler_k: int = 3) -> dict:
    alignment = podtrace.align(dumps)
    merged = podtrace.merge_timeline(dumps, alignment)
    kind = device_kind or costmodel.device_kind()
    peaks = costmodel.resolve_peaks(kind)
    roofline = podtrace.seam_roofline(dumps, peaks=peaks,
                                      extra_sites=extra_sites)
    rows = podtrace.skew_rows(dumps)
    return {
        "hosts": sorted(d["label"] for d in dumps),
        "run_id": dumps[0]["header"].get("run_id", "") if dumps else "",
        "events": len(merged),
        "alignment": alignment,
        "merged": merged,
        "sketches": podtrace.merge_sketches(dumps),
        "roofline": roofline,
        "device_kind": kind,
        "compute_wait": podtrace.compute_wait(dumps),
        "ingest": podtrace.ingest_breakdown(dumps),
        # one measurement, one rule: the same rows the live
        # StragglerTracker saw, judged by the shared elastic logic
        "skew": (elastic.skew_from_rows(rows, straggler_k=straggler_k)
                 if rows else None),
        "counters": {d["label"]: d["header"].get("counters") or {}
                     for d in dumps},
    }


def _fmt(x, pat="%10.3f"):
    return (pat % x) if isinstance(x, (int, float)) else "%10s" % "-"


def render(rep: dict, timeline_rows: int = 20) -> str:
    lines = ["pod report: %d host(s) %s  run_id=%r  %d merged events"
             % (len(rep["hosts"]), ",".join(rep["hosts"]),
                rep.get("run_id", ""), rep["events"])]
    al = rep["alignment"]
    lines += ["", "Clock alignment (reference %s)" % al["reference"],
              "------------------------------",
              "%-8s  %12s  %12s  %6s  %s"
              % ("host", "offset_s", "bound_s", "syncs", "consistent")]
    for lab, off in sorted(al["offsets"].items()):
        lines.append("%-8s  %s  %s  %6d  %s"
                     % (lab, _fmt(off.get("offset_s"), "%12.6f"),
                        _fmt(off.get("bound_s"), "%12.6f"),
                        off.get("sync_points", 0),
                        off.get("consistent")))
    lines += ["", "Seam roofline (device_kind=%s, ici peak=%s)"
              % (rep.get("device_kind"),
                 rep["roofline"].get("ici_bytes_per_sec")),
              "-" * 46,
              "%-28s  %12s  %6s  %10s  %12s  %10s"
              % ("site", "est_bytes", "calls", "span_s", "attained_GB/s",
                 "frac_peak")]
    for site, row in sorted(rep["roofline"]["sites"].items()):
        lines.append("%-28s  %12s  %6d  %s  %s  %s%s"
                     % (site, row.get("est_bytes"), row.get("calls", 0),
                        _fmt(row.get("span_s"), "%10.4f"),
                        _fmt(row.get("attained_gb_per_s"), "%12.4f"),
                        _fmt(row.get("frac_of_ici_peak"), "%10.4f"),
                        "" if row.get("modeled") else "  UNMODELED"))
    cw = rep.get("compute_wait") or {}
    if cw:
        lines += ["", "Compute vs collective wait (totals)",
                  "-----------------------------------"]
        for lab, row in sorted(cw.items()):
            lines.append("%-8s  compute %10.4fs  collective wait %10.4fs"
                         % (lab, row["compute_s"],
                            row["collective_wait_s"]))
    ing = rep.get("ingest") or {}
    if ing:
        lines += ["", "Ingest attribution (tokenizer vs bin vs H2D)",
                  "--------------------------------------------"]
        for lab, row in sorted(ing.items()):
            p = row["pcts"]
            lines.append("%-8s  %d chunks / %d rows   parse %s%%  "
                         "bin %s%%  h2d %s%%"
                         % (lab, row["chunks"], row["rows"],
                            p.get("parse_pct"), p.get("bin_pct"),
                            p.get("h2d_pct")))
    skew = rep.get("skew")
    if skew:
        lines += ["", "Skew (elastic.skew_from_rows — live-rule parity)",
                  "------------------------------------------------",
                  "iterations=%s max_phase_skew=%s barrier_wait_s=%s "
                  "persistent_straggler=%s"
                  % (skew.get("iterations_compared"),
                     skew.get("max_phase_skew"),
                     skew.get("barrier_wait_s"),
                     skew.get("persistent_straggler"))]
    sk = rep.get("sketches") or {}
    if sk:
        lines += ["", "Pod-wide sketches (merged percentiles)",
                  "--------------------------------------"]
        width = max(len(f) for f in sk)
        for fam, d in sorted(sk.items()):
            s = tracing.LatencySketch.from_dict(d)
            lines.append("%s  count %8d  p50 %s  p99 %s"
                         % (fam.ljust(width), s.count,
                            _fmt(s.quantile(0.5), "%10.1f"),
                            _fmt(s.quantile(0.99), "%10.1f")))
    lines += ["", "Merged timeline (first %d events)" % timeline_rows,
              "-" * 33]
    for ev in rep["merged"][:timeline_rows]:
        lines.append("%14.6f  %-6s  %s"
                     % (ev.get("t", 0.0), ev.get("_host"),
                        ev.get("kind")))
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="per-host trace dump JSONL")
    p.add_argument("--check", action="store_true",
                   help="validate pod-merge contracts; exit 1 on any "
                        "violation")
    p.add_argument("--json", action="store_true")
    p.add_argument("--wire", default=None,
                   help="extra per-site byte model (MULTICHIP_WIRE / "
                        "interconnect-snapshot / plain map JSON)")
    p.add_argument("--device-kind", default=None,
                   help="roofline peak lookup override (default: local "
                        "device kind)")
    p.add_argument("--straggler-k", type=int, default=3)
    p.add_argument("--timeline", type=int, default=20,
                   help="merged-timeline rows to render")
    args = p.parse_args()
    dumps = []
    findings = []
    for path in args.paths:
        try:
            dumps.append(podtrace.load_dump(path))
        except podtrace.PodTraceError as e:
            if args.check:
                findings.append(str(e))
                continue
            print("pod_report error: %s" % e, file=sys.stderr)
            return 2
    extra = None
    if args.wire:
        try:
            extra = _load_wire_arg(args.wire)
        except (OSError, ValueError) as e:
            print("pod_report error: --wire %s: %s" % (args.wire, e),
                  file=sys.stderr)
            return 2
    if args.check:
        if dumps:
            alignment = podtrace.align(dumps)
            merged = podtrace.merge_timeline(dumps, alignment)
            findings.extend(podtrace.check(dumps, alignment, merged))
            roof = podtrace.seam_roofline(
                dumps, peaks=costmodel.resolve_peaks(
                    args.device_kind or costmodel.device_kind()),
                extra_sites=extra)
            for site in roof["unmodeled"]:
                findings.append(
                    "seam %s has measured collective_sync spans but no "
                    "entry in the wire byte model — byte-model drift"
                    % site)
        for f in findings:
            print("POD-CHECK FAIL %s" % f)
        if findings:
            return 1
        print("pod-check ok: %d dump(s), merged clean" % len(dumps))
        return 0
    if not dumps:
        print("pod_report error: no dumps", file=sys.stderr)
        return 2
    rep = build_report(dumps, extra_sites=extra,
                       device_kind=args.device_kind,
                       straggler_k=args.straggler_k)
    if args.json:
        # the merged timeline dominates size; summarize it for JSON
        out = dict(rep)
        out["merged"] = {"events": len(rep["merged"])}
        print(json.dumps(out))
    else:
        print(render(rep, timeline_rows=args.timeline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
