"""Render / validate flight-recorder dumps (ISSUE 16).

Usage:
    python scripts/trace_report.py trace-1234-001.jsonl
    python scripts/trace_report.py --json  trace-1234-001.jsonl
    python scripts/trace_report.py --check trace-1234-001.jsonl [more...]

A dump (lightgbm_tpu/tracing.py, written atomically on clean close and
from the fault/crash paths) is one ``trace_header`` JSON line — reason,
ring occupancy, exact drop count, serialized latency sketches — followed
by the retained ring events oldest-first.  The default mode prints the
event-kind histogram, the per-component serve-latency attribution table
(mean / p99 / max, computed exactly from the raw ``serve_complete``
events) and the header sketches' streaming percentiles.

``--check`` validates the recorder's hard contracts and exits 1 on any
violation (2 on unreadable input), printing one line per finding:

  - unparseable JSONL, or a first line that is not a ``trace_header``;
  - the attribution identity: ``sum(components_ns) != wall_ns`` on ANY
    ``serve_complete`` event — the components must telescope exactly;
  - a negative component or negative wall;
  - event ordering: a request's ``serve_enqueue`` appearing after its
    ``serve_complete`` in ring order, or a completion with no enqueue in
    a dump whose header says nothing was dropped (dropped enqueues are
    tolerated — the ring drops oldest-first by design);
  - header bookkeeping: ``events`` not matching the event lines actually
    present, or ``dropped != max(0, appended - events)``;
  - live-monitor linkage (ISSUE 20): an ``slo_breach`` event without an
    integer ``window`` id, or one whose id has no matching
    ``monitor_window`` event in a dump whose header says nothing was
    dropped (the monitor files both into the same ring, breach after
    marker, so a complete ring must contain the pair);
  - host/process identity bookkeeping (pod-scope dumps, ISSUE 17): a
    ``process_index`` that is not an int in ``[0, process_count)``, a
    non-positive ``process_count``, or a non-string host/run_id;
  - multi-dump runs: passing dumps whose ``run_id`` headers disagree is
    a loud BadDump (exit 2 without --check) — merging traces from
    different runs is silently wrong, never a rendering choice.

Standalone stdlib script — it parses dumps by schema (the component
names mirror tracing.COMPONENTS) so it runs anywhere, including on dumps
scp'd off a crashed host.
"""
from __future__ import annotations

import argparse
import json
import math
import sys

# mirrors lightgbm_tpu.tracing.COMPONENTS (timeline order) — kept inline
# so the script stays dependency-free on crash-forensics hosts
COMPONENTS = ("queue", "linger", "coalesce", "dispatch", "walk", "scatter")


class BadDump(Exception):
    pass


def load(path: str):
    """-> (header dict, [event dicts]).  Raises BadDump on junk."""
    try:
        f = open(path)
    except OSError as e:
        raise BadDump("cannot read %s: %s" % (path, e))
    header, events = None, []
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise BadDump("%s:%d: unparseable JSONL (%s)"
                              % (path, lineno, e))
            if lineno == 1:
                if not isinstance(rec, dict) or "trace_header" not in rec:
                    raise BadDump("%s:1: first line is not a trace_header"
                                  % path)
                header = rec["trace_header"]
            elif not isinstance(rec, dict) or "kind" not in rec:
                raise BadDump("%s:%d: event line without a kind"
                              % (path, lineno))
            else:
                events.append(rec)
    if header is None:
        raise BadDump("%s: empty dump (no trace_header line)" % path)
    return header, events


def check(path: str, header: dict, events: list) -> list:
    """All contract violations in one dump (empty list = clean)."""
    bad = []
    if header.get("events") != len(events):
        bad.append("%s: header says %s events but %d lines present"
                   % (path, header.get("events"), len(events)))
    appended = int(header.get("appended", len(events)))
    want_drop = max(0, appended - len(events))
    if int(header.get("dropped", 0)) != want_drop:
        bad.append("%s: header dropped=%s but appended=%d with %d retained "
                   "events implies %d"
                   % (path, header.get("dropped"), appended, len(events),
                      want_drop))
    idx, cnt = header.get("process_index"), header.get("process_count")
    if cnt is not None and (not isinstance(cnt, int) or cnt < 1):
        bad.append("%s: header process_count=%r is not a positive int"
                   % (path, cnt))
    if idx is not None:
        if not isinstance(idx, int) or idx < 0 or (
                isinstance(cnt, int) and cnt >= 1 and idx >= cnt):
            bad.append("%s: header process_index=%r out of range for "
                       "process_count=%r" % (path, idx, cnt))
    for key in ("host", "run_id"):
        if key in header and not isinstance(header[key], str):
            bad.append("%s: header %s=%r is not a string"
                       % (path, key, header[key]))
    dropped = int(header.get("dropped", 0))
    enq_pos = {}
    for pos, ev in enumerate(events):
        if ev.get("kind") == "serve_enqueue" and "trace" in ev:
            enq_pos.setdefault(ev["trace"], pos)
    for pos, ev in enumerate(events):
        if ev.get("kind") != "serve_complete":
            continue
        tid = ev.get("trace")
        comps = ev.get("components_ns")
        wall = ev.get("wall_ns")
        if not isinstance(comps, dict) or not isinstance(wall, int):
            bad.append("%s: trace %s serve_complete missing "
                       "components_ns/wall_ns" % (path, tid))
            continue
        missing = [c for c in COMPONENTS if c not in comps]
        if missing:
            bad.append("%s: trace %s missing component(s) %s"
                       % (path, tid, ",".join(missing)))
            continue
        if wall < 0:
            bad.append("%s: trace %s negative wall_ns %d"
                       % (path, tid, wall))
        neg = [c for c in COMPONENTS if comps[c] < 0]
        if neg:
            bad.append("%s: trace %s negative component(s) %s"
                       % (path, tid, ",".join(neg)))
        total = sum(comps[c] for c in COMPONENTS)
        if total != wall:
            bad.append("%s: trace %s attribution identity broken: "
                       "sum(components)=%d != wall=%d"
                       % (path, tid, total, wall))
        pos_enq = enq_pos.get(tid)
        if pos_enq is None:
            if dropped == 0:
                bad.append("%s: trace %s completed with no enqueue event "
                           "in a dump with dropped=0" % (path, tid))
        elif pos_enq > pos:
            bad.append("%s: trace %s enqueue at line %d AFTER its "
                       "completion at line %d"
                       % (path, tid, pos_enq + 2, pos + 2))
    # live-monitor linkage (ISSUE 20): every slo_breach must point at a
    # monitor_window the ring retained — dropped>0 may have evicted the
    # marker, so the id check only binds on complete rings
    window_ids = {ev.get("window") for ev in events
                  if ev.get("kind") == "monitor_window"}
    for ev in events:
        if ev.get("kind") != "slo_breach":
            continue
        wid = ev.get("window")
        if not isinstance(wid, int):
            bad.append("%s: slo_breach event without an integer window id "
                       "(%r)" % (path, wid))
        elif dropped == 0 and wid not in window_ids:
            bad.append("%s: slo_breach window=%d has no monitor_window "
                       "event in a dump with dropped=0" % (path, wid))
    return bad


def _nearest_rank(sorted_vals, q):
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, int(math.ceil(q * n)) - 1))]


def check_run_mix(loaded) -> Optional[str]:
    """``[(path, header), ...]`` -> a finding when the dumps carry
    disagreeing run_ids (None = one run, or untagged dumps).  Untagged
    ("" / absent) headers mix with anything — pre-ISSUE-17 dumps stay
    renderable — but two DIFFERENT non-empty tags never do."""
    by_run = {}
    for path, header in loaded:
        rid = str(header.get("run_id") or "")
        if rid:
            by_run.setdefault(rid, []).append(path)
    if len(by_run) > 1:
        return ("mixing dumps from different runs: "
                + "; ".join("run_id=%r (%s)" % (rid, ", ".join(paths))
                            for rid, paths in sorted(by_run.items())))
    return None


def _sketch_quantile(sk: dict, q: float):
    """Nearest-rank quantile of one serialized sketch (growth/zero/
    buckets) — mirrors tracing.LatencySketch.quantile."""
    zero = int(sk.get("zero", 0))
    buckets = {int(i): int(c) for i, c in (sk.get("buckets") or {}).items()}
    total = zero + sum(buckets.values())
    if total == 0:
        return None
    rank = min(total - 1, max(0, int(math.ceil(q * total)) - 1))
    if rank < zero:
        return 0.0
    g = float(sk.get("growth", 1.05))
    seen = zero
    for i in sorted(buckets):
        seen += buckets[i]
        if rank < seen:
            return g ** (i + 0.5)
    return None


def summarize(header: dict, events: list) -> dict:
    kinds = {}
    for ev in events:
        kinds[ev.get("kind", "?")] = kinds.get(ev.get("kind", "?"), 0) + 1
    comps = {c: [] for c in COMPONENTS}
    walls = []
    for ev in events:
        if ev.get("kind") != "serve_complete":
            continue
        cn = ev.get("components_ns") or {}
        if all(c in cn for c in COMPONENTS):
            for c in COMPONENTS:
                comps[c].append(cn[c])
            walls.append(ev.get("wall_ns", 0))
    attribution = {}
    for c in COMPONENTS:
        vals = sorted(comps[c])
        if not vals:
            continue
        attribution[c] = {
            "count": len(vals),
            "mean_us": round(sum(vals) / len(vals) / 1e3, 1),
            "p99_us": round(_nearest_rank(vals, 0.99) / 1e3, 1),
            "max_us": round(vals[-1] / 1e3, 1),
        }
    walls.sort()
    out = {
        "reason": header.get("reason"),
        "pid": header.get("pid"),
        "host": header.get("host"),
        "process_index": header.get("process_index"),
        "process_count": header.get("process_count"),
        "run_id": header.get("run_id"),
        "counters": header.get("counters") or {},
        "ring_events": header.get("ring_events"),
        "events": len(events),
        "appended": header.get("appended"),
        "dropped": header.get("dropped"),
        "kinds": dict(sorted(kinds.items())),
        "attribution": attribution,
    }
    if walls:
        out["wall_us"] = {
            "count": len(walls),
            "mean_us": round(sum(walls) / len(walls) / 1e3, 1),
            "p99_us": round(_nearest_rank(walls, 0.99) / 1e3, 1),
            "max_us": round(walls[-1] / 1e3, 1),
        }
    sketches = {}
    for fam, sk in sorted((header.get("sketches") or {}).items()):
        zero = int(sk.get("zero", 0))
        cnt = zero + sum(int(c) for c in (sk.get("buckets") or {}).values())
        sketches[fam] = {
            "count": cnt,
            "p50": _sketch_quantile(sk, 0.50),
            "p99": _sketch_quantile(sk, 0.99),
            "p999": _sketch_quantile(sk, 0.999),
        }
    out["sketches"] = sketches
    return out


def render(path: str, s: dict) -> str:
    lines = ["trace report: %s" % path,
             "reason=%s pid=%s  ring %s/%s events (appended %s, "
             "dropped %s)"
             % (s.get("reason"), s.get("pid"), s.get("events"),
                s.get("ring_events"), s.get("appended"), s.get("dropped"))]
    if s.get("host") is not None or s.get("run_id"):
        lines.append("host=%s process=%s/%s run_id=%r"
                     % (s.get("host"), s.get("process_index"),
                        s.get("process_count"), s.get("run_id") or ""))
    lines += ["", "Event kinds", "-----------"]
    kinds = s.get("kinds") or {}
    if kinds:
        width = max(len(k) for k in kinds)
        for k, v in sorted(kinds.items()):
            lines.append("%s  %d" % (k.ljust(width), v))
    else:
        lines.append("(no events)")
    lines += ["", "Serve attribution (exact, from serve_complete events)",
              "-----------------------------------------------------"]
    attribution = s.get("attribution") or {}
    if attribution:
        lines.append("%-9s  %8s  %10s  %10s  %10s"
                     % ("component", "count", "mean us", "p99 us", "max us"))
        for c in COMPONENTS:
            a = attribution.get(c)
            if a is None:
                continue
            lines.append("%-9s  %8d  %10.1f  %10.1f  %10.1f"
                         % (c, a["count"], a["mean_us"], a["p99_us"],
                            a["max_us"]))
        w = s.get("wall_us")
        if w:
            lines.append("%-9s  %8d  %10.1f  %10.1f  %10.1f"
                         % ("wall", w["count"], w["mean_us"], w["p99_us"],
                            w["max_us"]))
    else:
        lines.append("(no serve_complete events in the retained window)")
    lines += ["", "Streaming sketches (live percentiles at dump time)",
              "--------------------------------------------------"]
    sketches = s.get("sketches") or {}
    if sketches:
        width = max(len(k) for k in sketches)

        def _f(x):
            return ("%10.1f" % x) if isinstance(x, (int, float)) \
                else "%10s" % "-"

        lines.append("%s  %8s  %10s  %10s  %10s"
                     % ("family".ljust(width), "count", "p50", "p99",
                        "p999"))
        for fam, pc in sorted(sketches.items()):
            lines.append("%s  %8d  %s  %s  %s"
                         % (fam.ljust(width), pc["count"], _f(pc["p50"]),
                            _f(pc["p99"]), _f(pc["p999"])))
    else:
        lines.append("(no sketches in header)")
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="trace dump JSONL file(s)")
    p.add_argument("--check", action="store_true",
                   help="validate contracts; exit 1 on any violation")
    p.add_argument("--json", action="store_true",
                   help="machine-readable summary instead of tables")
    args = p.parse_args()
    findings = []
    rc = 0
    loaded = []
    for path in args.paths:
        try:
            header, events = load(path)
        except BadDump as e:
            if args.check:
                findings.append(str(e))
                continue
            print("trace_report error: %s" % e, file=sys.stderr)
            return 2
        loaded.append((path, header, events))
    mix = check_run_mix([(p, h) for p, h, _e in loaded])
    if mix is not None:
        if not args.check:
            # a cross-run batch is a BadDump, not a rendering choice
            print("trace_report error: %s" % BadDump(mix),
                  file=sys.stderr)
            return 2
        findings.append(mix)
    for path, header, events in loaded:
        if args.check:
            findings.extend(check(path, header, events))
            continue
        s = summarize(header, events)
        if args.json:
            print(json.dumps({"path": path, **s}))
        else:
            print(render(path, s))
    if args.check:
        for f in findings:
            print("TRACE-CHECK FAIL %s" % f)
        if findings:
            return 1
        print("trace-check ok: %d dump(s) clean" % len(args.paths))
    return rc


if __name__ == "__main__":
    sys.exit(main())
