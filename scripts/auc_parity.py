"""Accuracy parity at bench scale: our depthwise TPU training vs the
compiled reference binary, 100 iterations on the same Higgs-style 1M-row
synthetic data, held-out AUC compared.

The depthwise grower's split ORDER differs from the reference (level order
vs global best-first), so trees are not expected to be identical — the
claim under test is that the MODEL QUALITY matches at equal iteration
count and config (BASELINE.json north star: "AUC parity").

Usage: python scripts/auc_parity.py [--rows N] [--iters K]
Writes nothing; prints a small report.  Needs the compiled reference at
/tmp/lightgbm_reference_build/lightgbm (tests/test_reference_differential.py
builds it).
"""
from __future__ import annotations

import argparse
import hashlib
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_data

REF_BIN = "/tmp/lightgbm_reference_build/lightgbm"

# Recorded reference-binary AUCs (BASELINE.md tables, measured on real
# hardware) for --skip-reference runs, PINNED to a digest of
# bench.make_data's output: the anchors are only valid for the exact
# data the reference was trained on, so a generator change (or a numpy
# RandomState behavior change) must be refused, not silently compared.
#   (rows, test_rows, iters, max_bin) -> (reference AUC, data digest)
RECORDED_REFERENCE_AUC = {
    (1_000_000, 200_000, 100, 255): (0.939544, "8d19841668b47c1c"),
    (1_000_000, 200_000, 30, 255): (0.904741, "8d19841668b47c1c"),
    (11_000_000, 500_000, 100, 255): (0.914417, "014912f2e0e95113"),
    (11_000_000, 500_000, 30, 255): (0.881476, "014912f2e0e95113"),
    (11_000_000, 1_000_000, 100, 63): (0.937752, "0166a0ce9ee1f963"),
}


def data_digest(x: np.ndarray, y: np.ndarray) -> str:
    """Digest of make_data's output: shape + a ~4096-row stride sample of
    features and labels (cheap even at 11M rows, and any RNG/generator
    drift perturbs every strided row)."""
    h = hashlib.sha256()
    h.update(np.asarray(x.shape, np.int64).tobytes())
    step = max(1, len(y) // 4096)
    h.update(np.ascontiguousarray(x[::step]).tobytes())
    h.update(np.ascontiguousarray(y[::step]).tobytes())
    return h.hexdigest()[:16]


def auc_manual(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC with tie handling (matches metric definitions)."""
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    allv = np.concatenate([pos, neg])
    order = np.argsort(allv, kind="mergesort")
    ranks = np.empty(len(allv))
    ranks[order] = np.arange(1, len(allv) + 1)
    sv = allv[order]
    # average ranks over ties
    uniq, inv, counts = np.unique(sv, return_inverse=True, return_counts=True)
    start = np.zeros(len(uniq))
    start[1:] = np.cumsum(counts)[:-1]
    avg = start + (counts + 1) / 2.0
    ranks = avg[inv[np.argsort(order)]]
    r_pos = ranks[: len(pos)].sum()
    n_pos, n_neg = len(pos), len(neg)
    return (r_pos - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--test-rows", type=int, default=200_000)
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--grow-policy", default="depthwise",
                    choices=["depthwise", "leafwise"])
    ap.add_argument("--hist-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"])
    ap.add_argument("--quant-rounding", default="nearest",
                    choices=["nearest", "stochastic"])
    ap.add_argument("--skip-reference", action="store_true",
                    help="train/evaluate only our side and compare "
                         "against the RECORDED reference AUC "
                         "(RECORDED_REFERENCE_AUC, from BASELINE.md); "
                         "each anchor is pinned to a digest of "
                         "bench.make_data's output and the run refuses "
                         "stale anchors")
    ap.add_argument("--max-bin", type=int, default=255,
                    help="bin budget for BOTH sides (the reference's "
                         "own default is 255; 63 is its documented "
                         "speed configuration, config.h:137 — the "
                         "quality gate must compare at matched budget)")
    args = ap.parse_args()

    x, y = make_data(args.rows + args.test_rows, 28)
    xtr, ytr = x[: args.rows], y[: args.rows]
    xte, yte = x[args.rows:], y[args.rows:]

    conf_common = dict(objective="binary", num_trees=args.iters,
                       learning_rate="0.1", num_leaves="255",
                       max_bin=str(args.max_bin),
                       min_data_in_leaf="100",
                       min_sum_hessian_in_leaf="10.0")

    # ---- ours (depthwise, fused chunks)
    import jax
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    ds = Dataset.from_arrays(xtr, ytr, max_bin=args.max_bin)
    cfg = OverallConfig()
    cfg.set({**{k: str(v) for k, v in conf_common.items()},
             "num_iterations": str(args.iters),
             "hist_dtype": args.hist_dtype,
             "quant_rounding": args.quant_rounding,
             "grow_policy": args.grow_policy}, require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))
    # perf_counter: monotonic (an NTP step would corrupt the duration)
    t0 = time.perf_counter()
    if args.grow_policy == "leafwise":
        # leaf-wise runs per-iteration: a fused chunk is ONE dispatch of
        # k x 254 histogram passes and crosses the environment's ~60 s
        # per-dispatch watchdog (BASELINE.md; same rule as bench.py)
        for _ in range(args.iters):
            if booster.train_one_iter(is_eval=False):
                break
    else:
        # keep each fused dispatch under the environment's ~60 s execution
        # watchdog: float paths cost ~1.8e-7 s/row/iter (bench.py's clamp)
        kmax = 64
        if args.hist_dtype != "int8" and args.rows > 4_000_000:
            kmax = max(1, int(40.0 / (args.rows * 1.8e-7)))
        done = 0
        while done < args.iters:
            k = min(kmax, args.iters - done)
            booster.train_chunk(k)
            done += k
    jax.block_until_ready(booster.score)
    t_ours = time.perf_counter() - t0
    ours_scores = booster.predict_raw(xte)
    ours_auc = auc_manual(yte, ours_scores)
    print(f"ours[{args.grow_policy}/{args.hist_dtype}/"
          f"{args.quant_rounding}/max_bin={args.max_bin}]: "
          f"{args.iters} iters in {t_ours:.1f}s "
          f"wall incl. jit compile (bench.py reports steady-state "
          f"throughput), test AUC {ours_auc:.6f}", flush=True)

    # ---- reference binary
    if args.skip_reference:
        # compare against the RECORDED reference AUC — but only after
        # verifying the data is byte-for-byte what the anchor was
        # recorded on (a make_data change would silently invalidate
        # every stored number)
        key = (args.rows, args.test_rows, args.iters, args.max_bin)
        anchor = RECORDED_REFERENCE_AUC.get(key)
        if anchor is None:
            print(f"no recorded reference anchor for rows={args.rows} "
                  f"test_rows={args.test_rows} iters={args.iters} "
                  f"max_bin={args.max_bin}; ours-only run")
            return 0
        ref_auc, want_digest = anchor
        got_digest = data_digest(x, y)
        if got_digest != want_digest:
            print(f"STALE ANCHOR: make_data digest {got_digest} != "
                  f"recorded {want_digest} — the generator (or numpy "
                  f"RandomState behavior) changed since the reference "
                  f"AUC was recorded; refusing the comparison.  Rerun "
                  f"without --skip-reference and re-record.",
                  file=sys.stderr)
            return 1
        print(f"recorded reference AUC {ref_auc:.6f} "
              f"(anchor digest {want_digest} verified)")
        print(f"AUC delta (ours - recorded reference): "
              f"{ours_auc - ref_auc:+.6f}")
        return 0
    if not os.path.exists(REF_BIN):
        print("reference binary not built; skipping reference side")
        return 0
    import pandas as pd
    import tempfile
    # unique workdir: concurrent invocations must not clobber each other
    wd = tempfile.mkdtemp(prefix="auc_parity_")
    tr_csv, te_csv = f"{wd}/train.csv", f"{wd}/test.csv"
    pd.DataFrame(np.column_stack([ytr, xtr])).to_csv(
        tr_csv, index=False, header=False, float_format="%.7g")
    pd.DataFrame(np.column_stack([yte, xte])).to_csv(
        te_csv, index=False, header=False, float_format="%.7g")
    conf = "\n".join(["task=train", f"data={tr_csv}",
                      f"num_trees={args.iters}"] +
                     [f"{k}={v}" for k, v in conf_common.items()
                      if k != "num_trees"] +
                     ["metric_freq=1000", "is_training_metric=false",
                      f"output_model={wd}/parity_model.txt"])
    open(f"{wd}/parity_train.conf", "w").write(conf + "\n")
    t0 = time.perf_counter()
    subprocess.run([REF_BIN, f"config={wd}/parity_train.conf"], check=True,
                   capture_output=True, text=True)
    t_ref = time.perf_counter() - t0
    open(f"{wd}/parity_pred.conf", "w").write(
        f"task=predict\ndata={te_csv}\ninput_model={wd}/parity_model.txt\n"
        f"output_result={wd}/parity_pred.txt\nis_sigmoid=false\n")
    subprocess.run([REF_BIN, f"config={wd}/parity_pred.conf"], check=True,
                   capture_output=True, text=True)
    ref_scores = np.loadtxt(f"{wd}/parity_pred.txt")
    import shutil
    shutil.rmtree(wd, ignore_errors=True)   # ~300+ MB of CSVs per run
    ref_auc = auc_manual(yte, ref_scores)
    print(f"reference: {args.iters} iters in {t_ref:.1f}s "
          f"({args.iters / t_ref:.2f} iters/s), test AUC {ref_auc:.6f}",
          flush=True)
    print(f"AUC delta (ours - reference): {ours_auc - ref_auc:+.6f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
