"""Measure feature-parallel ownership balancing: static contiguous slices
vs bin-count-balanced LPT assignment (the reference re-balances by bin
count, feature_parallel_tree_learner.cpp:27-44).

Uses a skewed-width dataset (half the features 255 bins, half 8 bins,
CLUSTERED so contiguous slices are maximally unbalanced) on the virtual
8-device CPU mesh — per-shard grower work scales with owned bin count, so
the slowest shard gates the step.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/fp_ownership_bench.py

Measured (2026-07-30, 8-dev CPU mesh, 200k x 32 with clustered widths
254/18-ish): static 143.2 s/iter, balanced 134.5 s/iter -> 1.06x.
Balanced (the default) is never worse; the gap grows with width skew and
shard count.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from lightgbm_tpu.config import OverallConfig
from lightgbm_tpu.io.dataset import Dataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel import create_parallel_learner
from lightgbm_tpu.parallel import learners as L


def main():
    rng = np.random.RandomState(0)
    n, f = 200_000, 32
    x = rng.randn(n, f)
    # first half: continuous (255 bins); second half: ~8 distinct values
    x[:, f // 2:] = np.round(x[:, f // 2:] * 2) / 2
    y = ((x[:, 0] - x[:, f // 2] + 0.3 * rng.randn(n)) > 0).astype(np.float32)
    ds = Dataset.from_arrays(x, y, max_bin=255)
    print("num_bins:", np.asarray(ds.num_bins), file=sys.stderr)

    results = {}
    for name, fn in (("static", L.static_ownership),
                     ("balanced", L.balanced_ownership)):
        cfg = OverallConfig()
        cfg.set({"objective": "binary", "num_leaves": "63",
                 "min_data_in_leaf": "100", "min_sum_hessian_in_leaf": "1.0",
                 "learning_rate": "0.1", "tree_learner": "feature",
                 "grow_policy": "depthwise", "num_machines": "8",
                 "num_iterations": "4"}, require_data=False)
        learner = create_parallel_learner(cfg)
        if name == "static":
            # static_ownership takes num_features, adapt the hook
            type(learner).ownership = staticmethod(
                lambda nb, s: L.static_ownership(len(nb), s))
        else:
            type(learner).ownership = staticmethod(L.balanced_ownership)
        b = GBDT()
        b.init(cfg.boosting_config, ds,
               create_objective(cfg.objective_type, cfg.objective_config),
               learner=learner)
        b.train_one_iter(is_eval=False)            # compile + warm
        # perf_counter: monotonic (an NTP step would corrupt the rate)
        t0 = time.perf_counter()
        for _ in range(3):
            b.train_one_iter(is_eval=False)
        jax.block_until_ready(b.score)
        results[name] = (time.perf_counter() - t0) / 3
        print(f"{name:9s}: {results[name]*1e3:8.1f} ms/iter", file=sys.stderr)
    L.FeatureParallelLearner.ownership = staticmethod(L.balanced_ownership)
    print(f"balanced speedup over static: "
          f"{results['static'] / results['balanced']:.2f}x", file=sys.stderr)


if __name__ == "__main__":
    main()
