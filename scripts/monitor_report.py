"""Render / validate live-monitor JSONL (ISSUE 20).

Usage:
    python scripts/monitor_report.py monitor.jsonl
    python scripts/monitor_report.py --json  monitor.jsonl
    python scripts/monitor_report.py --check monitor.jsonl [more...]

A monitor file (lightgbm_tpu/monitor.py, appended by the emitter thread
and closed on ``telemetry.disable()`` or from the faults.py crash path)
is one ``monitor_header`` line, then one ``monitor_window`` line per
closed interval — counter deltas, per-family latency-sketch deltas, the
SLO burn evaluation — and a final ``monitor_close`` line carrying the
serialized drift state.  The default mode prints the windowed series
(per-window percentiles of the SLO family, burn rates, breach marks)
and the close record's drift verdicts.

``--check`` validates the monitor's hard contracts and exits 1 on any
violation (2 on unreadable input), printing one line per finding:

  - unparseable JSONL, or a first line that is not a ``monitor_header``;
  - window ids not starting at 1 / not advancing by exactly 1;
  - a negative counter delta or a negative sketch-bucket delta — window
    deltas difference two monotone cumulative states, so negatives mean
    mixed baselines, never rounding;
  - delta/total conservation: for every counter and sketch family,
    ``total[w] == total[w-1] + delta[w]`` (a registry reset rebases the
    delta to the full total — tolerated, but only as the all-or-nothing
    rebase the monitor itself performs);
  - SLO burn arithmetic: the recorded per-window ``bad``/``total`` and
    the fast/slow burn rates are recomputed exactly from the emitted
    delta sketches (the same integers the monitor summed) and must
    match; the breach flag must equal ``fast >= FAST and slow >= SLOW``
    per the header's thresholds;
  - close-record bookkeeping: ``windows``/``emitted``/``breaches``
    must match the window lines actually present, at most one close,
    and no window lines after it;
  - drift verdicts: every recorded ``psi``/``aa_psi``/flag in the close
    record is RE-DERIVED from the serialized reference/live/A-A bucket
    maps — a tampered reference or a hand-edited verdict cannot agree
    with its own buckets; the A/A halves must also partition the live
    histogram (``a.count + b.count == live.count``).

Standalone stdlib script (schema constants mirror lightgbm_tpu.monitor)
so it runs anywhere, including on files scp'd off a crashed host.
"""
from __future__ import annotations

import argparse
import json
import math
import sys

# mirror lightgbm_tpu.monitor — inline so the script stays
# dependency-free on crash-forensics hosts
SLO_BUDGET = 0.01
FAST_BURN = 5.0
SLOW_BURN = 1.0
BURN_TOL = 1e-9


class BadDump(Exception):
    pass


def load(path: str):
    """-> (header, [window dicts], close-or-None, trailing-line count).
    Raises BadDump on junk."""
    try:
        f = open(path)
    except OSError as e:
        raise BadDump("cannot read %s: %s" % (path, e))
    header, windows, close = None, [], None
    after_close = 0
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise BadDump("%s:%d: unparseable JSONL (%s)"
                              % (path, lineno, e))
            if lineno == 1:
                if not isinstance(rec, dict) or "monitor_header" not in rec:
                    raise BadDump("%s:1: first line is not a monitor_header"
                                  % path)
                header = rec["monitor_header"]
            elif isinstance(rec, dict) and "monitor_window" in rec:
                if close is not None:
                    after_close += 1
                windows.append(rec["monitor_window"])
            elif isinstance(rec, dict) and "monitor_close" in rec:
                if close is not None:
                    after_close += 1
                close = rec["monitor_close"]
            else:
                raise BadDump("%s:%d: line is neither monitor_window nor "
                              "monitor_close" % (path, lineno))
    if header is None:
        raise BadDump("%s: empty file (no monitor_header line)" % path)
    return header, windows, close, after_close


# ---------------------------------------------------------------- sketches

def _sketch_count(sk: dict) -> int:
    return int(sk.get("zero", 0)) + sum(
        int(c) for c in (sk.get("buckets") or {}).values())


def _sketch_bad(sk: dict, threshold_us: float) -> int:
    g = float(sk.get("growth", 1.05))
    return sum(int(c) for i, c in (sk.get("buckets") or {}).items()
               if g ** (int(i) + 0.5) > threshold_us)


def _sketch_quantile(sk: dict, q: float):
    zero = int(sk.get("zero", 0))
    buckets = {int(i): int(c) for i, c in (sk.get("buckets") or {}).items()}
    total = zero + sum(buckets.values())
    if total == 0:
        return None
    rank = min(total - 1, max(0, int(math.ceil(q * total)) - 1))
    if rank < zero:
        return 0.0
    g = float(sk.get("growth", 1.05))
    seen = zero
    for i in sorted(buckets):
        seen += buckets[i]
        if rank < seen:
            return g ** (i + 0.5)
    return None


# ------------------------------------------------------------------- drift

def _hist_count(h: dict) -> int:
    return (int(h.get("zero", 0))
            + sum(int(c) for c in (h.get("pos") or {}).values())
            + sum(int(c) for c in (h.get("neg") or {}).values()))


def psi(ref: dict, live: dict, epsilon: float = 1e-4):
    """Recompute the PSI divergence from two serialized score
    histograms — the independent arithmetic the recorded verdicts must
    agree with (mirrors lightgbm_tpu.monitor.psi)."""
    if not ref or not live:
        return None
    rt, lt = _hist_count(ref), _hist_count(live)
    if rt == 0 or lt == 0:
        return None
    keys = {("z", 0)}
    for h in (ref, live):
        keys.update(("p", int(i)) for i in (h.get("pos") or {}))
        keys.update(("n", int(i)) for i in (h.get("neg") or {}))
    k = len(keys)
    total = 0.0
    for sign, i in keys:
        if sign == "z":
            rc, lc = int(ref.get("zero", 0)), int(live.get("zero", 0))
        else:
            side = "pos" if sign == "p" else "neg"
            rc = int((ref.get(side) or {}).get(str(i), 0))
            lc = int((live.get(side) or {}).get(str(i), 0))
        p = (rc + epsilon) / (rt + epsilon * k)
        q = (lc + epsilon) / (lt + epsilon * k)
        total += (q - p) * math.log(q / p)
    return total


def _close_to(a, b, tol: float = BURN_TOL) -> bool:
    if a is None or b is None:
        return a is None and b is None
    return abs(float(a) - float(b)) <= tol * max(1.0, abs(float(a)),
                                                 abs(float(b)))


# ------------------------------------------------------------------- check

def _check_conservation(path, what, wid, total, prev_total, delta, bad):
    """Shared counter/sketch-count conservation: totals are monotone
    cumulative, deltas difference them — except the all-or-nothing
    rebase after a registry reset, where deltas equal the new totals."""
    rebased = any(total.get(k, 0) < v for k, v in prev_total.items())
    expect = {}
    base = {} if rebased else prev_total
    for k, v in total.items():
        d = v - base.get(k, 0)
        if d:
            expect[k] = d
    if delta != expect:
        bad.append("%s: window %s %s deltas %r do not conserve against "
                   "totals (expected %r%s)"
                   % (path, wid, what, delta, expect,
                      ", rebased baseline" if rebased else ""))


def check(path: str, header: dict, windows: list, close, after_close: int
          ) -> list:
    """All contract violations in one monitor file (empty = clean)."""
    bad = []
    if not isinstance(header.get("interval_s"), (int, float)) \
            or header.get("interval_s", 0) <= 0:
        bad.append("%s: header interval_s=%r is not positive"
                   % (path, header.get("interval_s")))
    slo = header.get("slo")
    if slo is not None:
        for key in ("p99_us", "window_s"):
            if not isinstance(slo.get(key), (int, float)) \
                    or slo.get(key, 0) <= 0:
                bad.append("%s: header slo.%s=%r is not positive"
                           % (path, key, slo.get(key)))
        if slo.get("short_windows", 1) > slo.get("long_windows", 1):
            bad.append("%s: header slo short_windows=%r > long_windows=%r"
                       % (path, slo.get("short_windows"),
                          slo.get("long_windows")))
    if after_close:
        bad.append("%s: %d record(s) after the monitor_close line"
                   % (path, after_close))

    prev_counters = {}
    prev_sketch_counts = {}
    breach_seen = 0
    for pos, w in enumerate(windows):
        wid = w.get("window")
        if wid != pos + 1:
            bad.append("%s: window id %r at position %d (expected %d — ids "
                       "start at 1 and advance by 1)"
                       % (path, wid, pos + 1, pos + 1))
        counters = w.get("counters") or {}
        totals = w.get("counters_total") or {}
        neg = [k for k, v in counters.items() if v < 0]
        if neg:
            bad.append("%s: window %s negative counter delta(s) %s"
                       % (path, wid, ",".join(sorted(neg))))
        else:
            if pos == 0:
                # unknown arm-time baseline: deltas can only be bounded
                for k, v in counters.items():
                    if v > totals.get(k, 0):
                        bad.append("%s: window %s first-window delta %s=%d "
                                   "exceeds its cumulative total %d"
                                   % (path, wid, k, v, totals.get(k, 0)))
            else:
                _check_conservation(path, "counter", wid, totals,
                                    prev_counters, counters, bad)
        prev_counters = totals

        sketches = w.get("sketches") or {}
        sk_totals = w.get("sketch_counts_total") or {}
        sk_deltas = {}
        for fam, sk in sketches.items():
            if int(sk.get("zero", 0)) < 0 or any(
                    int(c) < 0 for c in (sk.get("buckets") or {}).values()):
                bad.append("%s: window %s negative sketch delta in family "
                           "%s" % (path, wid, fam))
            cnt = _sketch_count(sk)
            if cnt:
                sk_deltas[fam] = cnt
        if pos == 0:
            for fam, cnt in sk_deltas.items():
                if cnt > sk_totals.get(fam, 0):
                    bad.append("%s: window %s first-window sketch delta "
                               "%s=%d exceeds its cumulative count %d"
                               % (path, wid, fam, cnt,
                                  sk_totals.get(fam, 0)))
        else:
            _check_conservation(path, "sketch-count", wid, sk_totals,
                                prev_sketch_counts, sk_deltas, bad)
        prev_sketch_counts = sk_totals

        wslo = w.get("slo")
        if wslo is None:
            if slo is not None:
                bad.append("%s: window %s missing its slo block (header "
                           "declares an objective)" % (path, wid))
            continue
        if slo is None:
            bad.append("%s: window %s carries an slo block but the header "
                       "declares no objective" % (path, wid))
            continue
        fam = wslo.get("family")
        p99 = float(wslo.get("p99_us", 0))
        sk = sketches.get(fam)
        want_bad = 0 if sk is None else _sketch_bad(sk, p99)
        want_total = 0 if sk is None else _sketch_count(sk)
        if wslo.get("bad") != want_bad or wslo.get("total") != want_total:
            bad.append("%s: window %s slo bad/total %r/%r do not match the "
                       "window sketch (%d/%d)"
                       % (path, wid, wslo.get("bad"), wslo.get("total"),
                          want_bad, want_total))
        # recompute both burn rates over the trailing windows — the same
        # integer sums the monitor performed over its ring
        for label, nw, want_thresh in (
                ("fast", int(slo.get("short_windows", 1)), FAST_BURN),
                ("slow", int(slo.get("long_windows", 1)), SLOW_BURN)):
            b = t = 0
            for back in windows[max(0, pos + 1 - nw):pos + 1]:
                bsk = (back.get("sketches") or {}).get(fam)
                if not bsk:
                    continue
                b += _sketch_bad(bsk, p99)
                t += _sketch_count(bsk)
            want = 0.0 if t == 0 else (b / t) / SLO_BUDGET
            got = wslo.get("%s_burn" % label)
            if not isinstance(got, (int, float)) or not _close_to(got, want):
                bad.append("%s: window %s %s_burn=%r but recomputing over "
                           "the trailing %d window(s) gives %.6g"
                           % (path, wid, label, got, nw, want))
        want_breach = bool(
            isinstance(wslo.get("fast_burn"), (int, float))
            and isinstance(wslo.get("slow_burn"), (int, float))
            and wslo["fast_burn"] >= float(slo.get("fast_burn", FAST_BURN))
            and wslo["slow_burn"] >= float(slo.get("slow_burn", SLOW_BURN)))
        if bool(wslo.get("breach")) != want_breach:
            bad.append("%s: window %s breach=%r contradicts its own burn "
                       "rates (fast=%r slow=%r)"
                       % (path, wid, wslo.get("breach"),
                          wslo.get("fast_burn"), wslo.get("slow_burn")))
        if wslo.get("breach"):
            breach_seen += 1

    if close is not None:
        last = windows[-1]["window"] if windows else 0
        if close.get("windows") != last:
            bad.append("%s: close says %r windows but the last window line "
                       "is id %r (disarm ticks the tail window first, so "
                       "they must agree)" % (path, close.get("windows"),
                                             last))
        if close.get("emitted") != len(windows):
            bad.append("%s: close says emitted=%r but %d window lines are "
                       "present" % (path, close.get("emitted"),
                                    len(windows)))
        if close.get("breaches") != breach_seen:
            bad.append("%s: close says breaches=%r but %d window(s) carry "
                       "breach=true" % (path, close.get("breaches"),
                                        breach_seen))
        for key, d in sorted((close.get("drift") or {}).items()):
            live = d.get("live") or {}
            a, b = d.get("a") or {}, d.get("b") or {}
            if _hist_count(a) + _hist_count(b) != _hist_count(live):
                bad.append("%s: drift %s A/A halves (%d + %d) do not "
                           "partition the live histogram (%d)"
                           % (path, key, _hist_count(a), _hist_count(b),
                              _hist_count(live)))
            want_psi = psi(d.get("reference"), live)
            if not _close_to(d.get("psi"), want_psi):
                bad.append("%s: drift %s recorded psi=%r but the "
                           "serialized reference/live buckets give %r — "
                           "tampered reference or verdict"
                           % (path, key, d.get("psi"), want_psi))
            thresh = d.get("threshold")
            want_flag = bool(want_psi is not None
                             and isinstance(thresh, (int, float))
                             and want_psi > thresh)
            if bool(d.get("drift")) != want_flag:
                bad.append("%s: drift %s flag=%r contradicts psi=%r vs "
                           "threshold=%r" % (path, key, d.get("drift"),
                                             want_psi, thresh))
            want_aa = psi(a, b)
            if not _close_to(d.get("aa_psi"), want_aa):
                bad.append("%s: drift %s recorded aa_psi=%r but the A/A "
                           "buckets give %r" % (path, key, d.get("aa_psi"),
                                                want_aa))
    return bad


# ------------------------------------------------------------------ render

def summarize(header: dict, windows: list, close) -> dict:
    slo = header.get("slo")
    fam = (slo or {}).get("family") or "serve_wall_us"
    series = []
    for w in windows:
        sk = (w.get("sketches") or {}).get(fam)
        row = {
            "window": w.get("window"),
            "dur_s": round(float(w.get("t1", 0)) - float(w.get("t0", 0)), 3),
            "count": 0 if sk is None else _sketch_count(sk),
            "p50_us": None if sk is None else _sketch_quantile(sk, 0.50),
            "p99_us": None if sk is None else _sketch_quantile(sk, 0.99),
            "counters": w.get("counters") or {},
        }
        if w.get("slo"):
            row["fast_burn"] = w["slo"].get("fast_burn")
            row["slow_burn"] = w["slo"].get("slow_burn")
            row["breach"] = bool(w["slo"].get("breach"))
        series.append(row)
    out = {
        "interval_s": header.get("interval_s"),
        "run_id": header.get("run_id"),
        "host": header.get("host"),
        "pid": header.get("pid"),
        "slo": slo,
        "family": fam,
        "windows": series,
        "breaches": sum(1 for r in series if r.get("breach")),
    }
    if close is not None:
        out["close"] = {
            "reason": close.get("reason"),
            "windows": close.get("windows"),
            "breaches": close.get("breaches"),
            "drift": {
                key: {"n": d.get("n"), "psi": d.get("psi"),
                      "drift": d.get("drift"), "aa_psi": d.get("aa_psi"),
                      "aa_bound": d.get("aa_bound")}
                for key, d in sorted((close.get("drift") or {}).items())},
        }
    return out


def render(path: str, s: dict) -> str:
    lines = ["monitor report: %s" % path,
             "interval=%ss host=%s pid=%s run_id=%r  windows=%d "
             "breaches=%d"
             % (s.get("interval_s"), s.get("host"), s.get("pid"),
                s.get("run_id") or "", len(s.get("windows") or []),
                s.get("breaches", 0))]
    slo = s.get("slo")
    if slo:
        lines.append("slo: %s p99 <= %gus over %gs (fast %gx over %d "
                     "window(s), slow %gx over %d)"
                     % (slo.get("family"), slo.get("p99_us"),
                        slo.get("window_s"), slo.get("fast_burn"),
                        slo.get("short_windows"), slo.get("slow_burn"),
                        slo.get("long_windows")))
    lines += ["", "Windowed series (%s)" % s.get("family"),
              "-" * (18 + len(str(s.get("family"))))]

    def _f(x, fmt="%9.1f"):
        return (fmt % x) if isinstance(x, (int, float)) else "%9s" % "-"

    lines.append("%6s  %7s  %7s  %9s  %9s  %9s  %9s  %s"
                 % ("window", "dur s", "count", "p50 us", "p99 us",
                    "fast", "slow", "breach"))
    for r in s.get("windows") or []:
        lines.append("%6s  %7.3f  %7d  %s  %s  %s  %s  %s"
                     % (r["window"], r["dur_s"], r["count"],
                        _f(r["p50_us"]), _f(r["p99_us"]),
                        _f(r.get("fast_burn"), "%9.3f"),
                        _f(r.get("slow_burn"), "%9.3f"),
                        "BREACH" if r.get("breach") else ""))
    if not s.get("windows"):
        lines.append("(no windows)")
    close = s.get("close")
    if close:
        lines += ["", "Close (%s)" % close.get("reason"),
                  "------------------"]
        for key, d in sorted((close.get("drift") or {}).items()):
            lines.append("%s: n=%s psi=%s drift=%s aa_psi=%s (bound %s)"
                         % (key, d.get("n"),
                            "-" if d.get("psi") is None
                            else "%.4f" % d["psi"],
                            d.get("drift"),
                            "-" if d.get("aa_psi") is None
                            else "%.4f" % d["aa_psi"],
                            d.get("aa_bound")))
        if not close.get("drift"):
            lines.append("(no drift state)")
    else:
        lines += ["", "(no close record — emitter still live, or the "
                      "process died before the fault hatch could flush)"]
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="monitor JSONL file(s)")
    p.add_argument("--check", action="store_true",
                   help="validate contracts; exit 1 on any violation")
    p.add_argument("--json", action="store_true",
                   help="machine-readable summary instead of tables")
    args = p.parse_args()
    findings = []
    for path in args.paths:
        try:
            header, windows, close, after = load(path)
        except BadDump as e:
            if args.check:
                findings.append(str(e))
                continue
            print("monitor_report error: %s" % e, file=sys.stderr)
            return 2
        if args.check:
            findings.extend(check(path, header, windows, close, after))
            continue
        s = summarize(header, windows, close)
        if args.json:
            print(json.dumps({"path": path, **s}))
        else:
            print(render(path, s))
    if args.check:
        for f in findings:
            print("MONITOR-CHECK FAIL %s" % f)
        if findings:
            return 1
        print("monitor-check ok: %d file(s) clean" % len(args.paths))
    return 0


if __name__ == "__main__":
    sys.exit(main())
