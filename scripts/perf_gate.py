"""Perf-regression gate over the BENCH/MULTICHIP round trajectory.

Reads the repo's bench history (``BENCH_r*.json`` wrappers with a
``parsed`` bench record, raw ``bench.py`` JSON lines, and
``MULTICHIP_r*.json`` smoke records) and flags regressions in the LATEST
round against the earlier trajectory:

- **throughput**: the headline ``value`` and the satellite rate keys
  (parity/leafwise_int8/maxbin63 rows, ``vs_cuda``) must not drop below
  the prior median by more than the recorded noise band — the
  ``spread``/``parity_spread``-style (max-min)/median markers bench.py
  records for exactly this purpose (sigma = band/2; flagged beyond
  ``--sigma-mult`` sigmas, default 3);
- **serving latency + zero-tolerance contracts** (ISSUE 13): the
  ``bench_serve`` lane's ``serve_p99_us`` must not GROW beyond the wide
  observability band (LATENCY_KEYS), and ``predict_recompiles`` /
  ``serve_recompiles`` / ``serve_dropped`` / ``serve_misscored`` are
  ABSOLUTE findings — any nonzero on the latest round fails the gate
  with no trajectory at all (the closed-program-ladder and
  zero-drop-hot-swap contracts);
- **attained fraction**: the roofline block's ``frac_of_peak_flops`` /
  ``frac_of_peak_bw`` per phase, when present — a throughput number can
  hide a kernel regression behind a faster host, the attained fraction
  cannot;
- **checkpoint contracts** (ISSUE 14): ``ckpt_overhead_pct`` (the
  bench_ckpt lane's checkpointing-on vs off slowdown) rides the
  must-not-grow latency lane, and ``ckpt_restore_exact`` recorded False
  on ANY round — a same-topology restore that was not bit-identical —
  is an absolute finding, as are ``restore_match``/``metrics_complete``
  False in a multichip round's ``MULTICHIP_ELASTIC`` kill-restart row;
- **multichip**: a round whose smoke run went ok -> not-ok, plus the
  ISSUE-5 distributed-observability trajectory: the ``skew`` block's
  ``max_phase_skew`` (cross-host per-phase dispersion must not grow
  beyond the noise band — a growing ratio is a new straggler or an
  unbalanced schedule) and the ``interconnect`` attained GB/s (must not
  drop — a collective-route regression hides behind a healthy ok flag).
  The block is read from the record itself or parsed out of the smoke
  run's ``tail`` (dryrun_multichip prints one ``MULTICHIP_OBS`` JSON
  line).
- **pod-scope observability** (ISSUE 17): the ``MULTICHIP_PODTRACE``
  line's merge bookkeeping.  Three ABSOLUTE findings need no trajectory
  — ``alignment_ok`` False (a host's clock-offset estimates disagree
  beyond the recorded collective-duration bounds, i.e. the alignment
  error exceeded the bound the dump itself recorded),
  ``check_findings``/``unmodeled`` nonzero (the real pod_report --check
  contracts: header bookkeeping, event conservation, attribution
  identity, byte-model coverage), and ``parity`` False (the post-mortem
  straggler verdict diverged from the live StragglerTracker's over the
  same measurements) — plus a must-not-grow lane on the normalized
  merge overhead (``merge_ms_per_kevent``, wide observability floor:
  tiny smokes, timing-noise-dominated).
- **wire bytes** (ISSUE 9): the ``MULTICHIP_WIRE`` line's logical
  ``wire_bytes_per_iter`` per tree learner (data / hybrid / voting at
  the F=28, B=255 schema).  These are DETERMINISTIC — traced shapes x
  loop estimates, no timing noise — so the must-not-grow band is the
  tight rate-key floor, compared only across rounds at the same device
  count; and two ABSOLUTE findings need no trajectory at all: hybrid
  recording >= pure-DP bytes (the 2-D owned-block restriction stopped
  paying) and voting recording >= hybrid bytes (the voted exchange
  stopped paying).

Entries are grouped by their ``metric`` name (an 11M round is never
compared to a 1M round) and, when the ``host`` block is present
(bench.py records device_kind/jax versions/git SHA since ISSUE 4), the
gate REFUSES to compare rounds measured on different device kinds
(exit 2) — cross-hardware "regressions" are noise.  Rounds without a
host block (the pre-ISSUE-4 history) are assumed comparable.

Usage (the documented pre-merge check):

    python scripts/perf_gate.py --check 'BENCH_r*.json' 'MULTICHIP_r*.json'

Exit codes: 0 = no regression, 1 = regression flagged, 2 = bad input /
cross-hardware mix.  ``--json`` prints the machine-readable report.
Runs as a tier-1 unit test (tests/test_perf_gate.py: must flag an
injected 3-sigma regression, must pass the real r01+ trajectory).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# satellite rate keys checked next to the headline "value", with the
# spread key that prices their noise band
RATE_KEYS: Tuple[Tuple[str, str], ...] = (
    ("value", "spread"),
    ("vs_cuda", "spread"),
    ("parity_leafwise_f32_iters_per_sec", "parity_spread"),
    ("leafwise_int8_iters_per_sec", "leafwise_int8_spread"),
    ("maxbin63_iters_per_sec", "maxbin63_spread"),
    # mixed-bin packed path, pinned explicitly ON (ISSUE 6): guards the
    # per-class histogram schedule even if the headline's auto
    # resolution ever changes
    ("mixedbin_iters_per_sec", "mixedbin_spread"),
    # the COMPOSED configuration (ISSUE 12): block-local mixed-bin
    # packing on the 2-D hybrid mesh, pinned explicitly ON — the lane
    # that proves the speed tiers multiply instead of exclude
    ("mixedbin_hybrid_iters_per_sec", "mixedbin_hybrid_spread"),
    # serving lanes (ISSUE 7, bench.py --bench-predict): predictions/sec
    # off the compiled serving engine at the gated bucket shapes — the
    # 64k throughput bucket (f32 and int8 ensembles) and the 1k
    # latency-tier bucket.  Latency percentiles (p50/p99) and the
    # bfs-vs-scan A/B ratio ride in the record ungated (lower-is-better
    # keys don't fit the drop-gate; the ratio is informational).
    ("predict_b65536_rows_per_sec", "predict_b65536_spread"),
    ("predict_int8_b65536_rows_per_sec", "predict_int8_b65536_spread"),
    ("predict_b1024_rows_per_sec", "predict_b1024_spread"),
    # the 32-row latency-tier bucket: recorded with a spread marker
    # since r06 but never gated — the exact stale-emission drift the
    # graftlint D2 census now fails the gate on (ISSUE 15)
    ("predict_b32_rows_per_sec", "predict_b32_spread"),
    # streaming ingestion (ISSUE 8, bench.py --bench-ingest): rows/sec
    # for the chunked parse->bin->HBM pipeline.  The double-buffer A/B,
    # H2D GB/s and the peak-RSS assertion ride the record ungated
    # (ingest_rss_ok false would be a correctness bug, not a trajectory
    # drift — the bench lane itself surfaces it).
    ("ingest_rows_per_sec", "ingest_spread"),
    # elastic serving (ISSUE 13, bench.py --bench-serve): sustained
    # rows/sec through the coalescing ServingFront under the open-loop
    # load generator.  The p99 lane rides LATENCY_KEYS (must-not-grow);
    # recompiles/dropped/misscored are absolute findings below.
    ("serve_rows_per_sec", "serve_spread"),
)

# lower-is-better keys gated in the GROW direction (ISSUE 13): the p99
# under open-loop load.  Latency tails on a shared host swing far more
# than throughput medians, so the band floor is the wide observability
# floor (like the multichip skew series): the lane catches
# order-of-magnitude breaks — a lost coalescing path, a swap stall in
# the request path — not percent drift.
LATENCY_KEYS: Tuple[Tuple[str, str], ...] = (
    ("serve_p99_us", "serve_spread"),
    # checkpoint cost (ISSUE 14, bench.py --bench-ckpt): percent slowdown
    # of the training loop with async checkpointing ON vs OFF.  Lower is
    # better; gated must-not-grow at the wide observability floor (the
    # overhead is a small difference of two noisy wall times).
    ("ckpt_overhead_pct", "ckpt_spread"),
    # flight-recorder cost (ISSUE 16, bench.py --bench-serve): percent
    # serve throughput lost with the recorder armed, from interleaved
    # recorder-on/off segments of the same open-loop load.  "Always-on"
    # is only honest while this stays flat — gated must-not-grow at the
    # wide observability floor (a small difference of two noisy rates).
    ("trace_overhead_pct", "trace_spread"),
    # live-monitor cost (ISSUE 20, bench.py --bench-serve): percent
    # serve throughput lost with the monitor armed ON TOP of the
    # recorder, from interleaved monitor-on/off segments — same honesty
    # contract as trace_overhead_pct, same wide band.
    ("monitor_overhead_pct", "monitor_spread"),
)

# mirror of lightgbm_tpu.monitor.AA_PSI_BOUND — the documented A/A
# false-positive bound the bench's drift_aa_psi must stay under (kept
# inline: the gate runs on hosts without the package)
AA_PSI_BOUND = 0.05

# absolute zero-tolerance keys (no trajectory needed): any nonzero on
# the LATEST round is a finding.  predict/serve recompiles break the
# closed-program-ladder contract; dropped/misscored requests break the
# hot-swap zero-drop contract (ISSUE 13).
ABSOLUTE_ZERO_KEYS: Tuple[Tuple[str, str], ...] = (
    ("predict_recompiles",
     "serving engine recompiled at a bucketed batch shape (the "
     "compiled-program ladder is no longer closed)"),
    ("serve_recompiles",
     "elastic-serving lane recompiled at a coalesced batch shape (the "
     "compiled-program ladder is no longer closed under load)"),
    ("serve_dropped",
     "request(s) dropped across the mid-load hot swap — the "
     "drain-and-flip zero-drop contract is broken"),
    ("serve_misscored",
     "request(s) misscored across the mid-load hot swap (a result "
     "matched neither the old nor the new engine — a torn swap)"),
    ("trace_dropped_at_default",
     "flight-recorder ring overflowed at the DEFAULT trace_ring_events "
     "during a measured serve window (ISSUE 16) — the last-N-events "
     "crash timeline no longer covers a single load segment"),
)

# absolute must-be-true keys (ISSUE 14): a recorded value of exactly
# False on ANY round in the trajectory is a finding — these are
# correctness contracts, not trajectories.  Absent keys (older rounds)
# are fine.
ABSOLUTE_TRUE_KEYS: Tuple[Tuple[str, str], ...] = (
    ("ckpt_restore_exact",
     "a checkpoint restore was not bit-identical on the same topology "
     "(model text / scores / RNG streams diverged from the "
     "uninterrupted run)"),
)

DEFAULT_FLOOR = 0.02      # minimum relative noise band when none recorded
DEFAULT_SIGMA_MULT = 3.0
# noise-band floor for the multichip skew/interconnect series (no
# recorded spread; tiny smoke runs -> timing-noise-dominated) — also the
# LATENCY_KEYS floor, for the same reason
_OBS_FLOOR = 0.5


class GateError(Exception):
    """Malformed input or an invalid comparison (exit code 2)."""


def _round_of(path: str, data: dict) -> int:
    n = data.get("n") or data.get("round")
    if isinstance(n, int):
        return n
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else 0


def load_entry(path: str) -> dict:
    """One trajectory entry: {kind: bench|multichip, round, rec, path}."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise GateError(f"{path}: unreadable bench JSON ({e})")
    if not isinstance(data, dict):
        raise GateError(f"{path}: expected a JSON object")
    if isinstance(data.get("parsed"), dict):
        rec, kind = data["parsed"], "bench"
    elif "metric" in data:
        rec, kind = data, "bench"
    elif "n_devices" in data or "ok" in data:
        rec, kind = data, "multichip"
        _attach_multichip_obs(rec)
    else:
        raise GateError(f"{path}: unrecognized bench record "
                        "(no 'parsed', 'metric' or multichip keys)")
    return {"kind": kind, "round": _round_of(path, data), "rec": rec,
            "path": path}


def _attach_multichip_obs(rec: dict) -> None:
    """Surface the distributed-observability block on a multichip record:
    either already present as ``skew``/``interconnect``/``wire`` keys, or
    parsed from the smoke run's captured ``tail`` (dryrun_multichip
    prints one ``MULTICHIP_OBS <json>`` line and, since ISSUE 9, one
    ``MULTICHIP_WIRE <json>`` line).  Malformed/absent lines leave the
    record untouched — earlier rounds simply have no such series."""
    tail = rec.get("tail")
    lines = tail.splitlines() if isinstance(tail, str) else []
    if "skew" not in rec:
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_OBS "):
                continue
            try:
                obs = json.loads(line[len("MULTICHIP_OBS "):])
            except ValueError:
                break
            if isinstance(obs, dict):
                for key in ("skew", "interconnect", "simulated_hosts"):
                    if key in obs:
                        rec[key] = obs[key]
            break
    if "wire" not in rec:
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_WIRE "):
                continue
            try:
                wire = json.loads(line[len("MULTICHIP_WIRE "):])
            except ValueError:
                break
            if isinstance(wire, dict):
                rec["wire"] = wire
            break
    if "elastic" not in rec:
        # ISSUE 14: the kill-a-process-mid-run row prints one
        # MULTICHIP_ELASTIC JSON line (SIGKILL between iterations →
        # restart from the latest checkpoint on a shrunk topology)
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_ELASTIC "):
                continue
            try:
                el = json.loads(line[len("MULTICHIP_ELASTIC "):])
            except ValueError:
                break
            if isinstance(el, dict):
                rec["elastic"] = el
            break
    if "podtrace" not in rec:
        # ISSUE 17: the pod-scope observability row prints one
        # MULTICHIP_PODTRACE JSON line (two real processes -> per-host
        # dumps -> pod_report --check on the merge)
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_PODTRACE "):
                continue
            try:
                pt = json.loads(line[len("MULTICHIP_PODTRACE "):])
            except ValueError:
                break
            if isinstance(pt, dict):
                rec["podtrace"] = pt
            break
    if "monitor" not in rec:
        # ISSUE 20: the live-monitor row prints one MULTICHIP_MONITOR
        # JSON line (induced latency bulge -> SLO burn breach;
        # shifted-score swap -> drift verdict; A/A self-check under its
        # bound; monitor_report/trace_report --check clean)
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_MONITOR "):
                continue
            try:
                mon = json.loads(line[len("MULTICHIP_MONITOR "):])
            except ValueError:
                break
            if isinstance(mon, dict):
                rec["monitor"] = mon
            break
    if "sharded_ingest" not in rec:
        # ISSUE 18: the multi-host sharded-ingest row prints one
        # MULTICHIP_SHARDED_INGEST JSON line (every rank parses only
        # its own row shard's byte ranges — per-host parsed-row counts
        # must tile the dataset exactly, zero overlap)
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("MULTICHIP_SHARDED_INGEST "):
                continue
            try:
                si = json.loads(line[len("MULTICHIP_SHARDED_INGEST "):])
            except ValueError:
                break
            if isinstance(si, dict):
                rec["sharded_ingest"] = si
            break


def _fractions(rec: dict) -> Dict[str, float]:
    """Flatten the roofline attained fractions into gate keys."""
    out = {}
    phases = (rec.get("roofline") or {}).get("phases") or {}
    for phase, blk in phases.items():
        for f in ("frac_of_peak_flops", "frac_of_peak_bw"):
            v = blk.get(f)
            if isinstance(v, (int, float)):
                out[f"roofline/{phase}/{f}"] = float(v)
    return out


def _series(entries: List[dict], key: str) -> List[Tuple[int, float]]:
    out = []
    for e in entries:
        v = e["rec"].get(key)
        if key.startswith("roofline/"):
            v = _fractions(e["rec"]).get(key)
        if isinstance(v, (int, float)):
            out.append((e["round"], float(v)))
    return out


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _noise_band(entries: List[dict], spread_key: str, floor: float) -> float:
    """Noise band from the PRIOR rounds only (callers pass entries[:-1]):
    a regressed round must not widen its own allowance by also reporting
    a wide spread (self-masking)."""
    spreads = [float(e["rec"][spread_key]) for e in entries
               if isinstance(e["rec"].get(spread_key), (int, float))]
    return max(spreads + [floor])


def _check_group(metric: str, entries: List[dict], floor: float,
                 sigma_mult: float, allow_cross_hardware: bool,
                 findings: List[dict]) -> None:
    entries = sorted(entries, key=lambda e: e["round"])
    kinds = {e["rec"].get("host", {}).get("device_kind")
             for e in entries if isinstance(e["rec"].get("host"), dict)}
    kinds.discard(None)
    if len(kinds) > 1 and not allow_cross_hardware:
        raise GateError(
            f"{metric}: trajectory mixes device kinds {sorted(kinds)} — "
            "cross-hardware comparisons refused "
            "(--allow-cross-hardware to override)")
    # absolute zero-tolerance contracts (ISSUE 7 no-recompile, ISSUE 13
    # zero-drop hot swap): any nonzero on the latest round is a finding,
    # no trajectory needed
    for akey, detail in ABSOLUTE_ZERO_KEYS:
        v = entries[-1]["rec"].get(akey)
        if isinstance(v, (int, float)) and v > 0:
            findings.append({
                "metric": metric, "key": akey,
                "latest_round": entries[-1]["round"],
                "latest": v, "baseline": 0,
                "detail": detail,
            })
    # must-be-true contracts (ISSUE 14): checked on EVERY recorded round
    # — a round that recorded a non-bit-identical checkpoint restore is
    # a finding forever, not only while it is the latest
    for akey, detail in ABSOLUTE_TRUE_KEYS:
        for e in entries:
            if e["rec"].get(akey) is False:
                findings.append({
                    "metric": metric, "key": akey,
                    "latest_round": e["round"],
                    "latest": False, "baseline": True,
                    "detail": detail,
                })
    _check_mixedbin_resolution(metric, entries[-1], findings)
    _check_ingest_workers(metric, entries, findings)
    _check_drift_slo(metric, entries[-1], findings)
    if len(entries) < 2:
        return
    latest_round = entries[-1]["round"]
    keys = [k for k, _ in RATE_KEYS]
    keys += sorted({k for e in entries for k in _fractions(e["rec"])})
    spread_of = dict(RATE_KEYS)
    for key in keys:
        series = _series(entries, key)
        if len(series) < 2 or series[-1][0] != latest_round:
            continue
        prior = [v for r, v in series[:-1]]
        latest = series[-1][1]
        baseline = _median(prior)
        if baseline <= 0:
            continue
        band = _noise_band(entries[:-1], spread_of.get(key, "spread"),
                           floor)
        sigma = band / 2.0
        threshold = baseline * (1.0 - sigma_mult * sigma)
        if latest < threshold:
            findings.append({
                "metric": metric, "key": key,
                "latest_round": latest_round,
                "latest": latest, "baseline": round(baseline, 6),
                "drop": round(1.0 - latest / baseline, 4),
                "allowed_drop": round(sigma_mult * sigma, 4),
            })
    # lower-is-better latency lanes (ISSUE 13): must not GROW beyond
    # the wide observability band — p99 tails are timing-noise-dominated
    # on shared hosts, so this catches order-of-magnitude breaks
    for key, spread_key in LATENCY_KEYS:
        series = _series(entries, key)
        if len(series) < 2 or series[-1][0] != latest_round:
            continue
        prior = [v for r, v in series[:-1]]
        latest = series[-1][1]
        baseline = _median(prior)
        if baseline <= 0:
            continue
        band = max(_noise_band(entries[:-1], spread_key, floor),
                   _OBS_FLOOR)
        sigma = band / 2.0
        if latest > baseline * (1.0 + sigma_mult * sigma):
            findings.append({
                "metric": metric, "key": key,
                "latest_round": latest_round,
                "latest": latest, "baseline": round(baseline, 6),
                "drop": round(latest / baseline - 1.0, 4),
                "allowed_drop": round(sigma_mult * sigma, 4),
            })


def _check_mixedbin_resolution(metric: str, latest: dict,
                               findings: List[dict]) -> None:
    """ISSUE 12 absolute finding, no trajectory needed: a recorded
    hybrid/voting round whose config requested ``mixed_bin`` auto/true
    on a mixed-cardinality table but whose booster resolved the UNIFORM
    layout — the silent fallback the pre-ISSUE-12 ``needs_uniform_layout``
    gate used to take — must not pass the gate unnoticed.  Reads the
    bench record's resolution keys (``tree_learner`` /
    ``mixed_bin_requested`` / ``mixedbin_expected`` / ``mixed_bin_on``,
    both bare for a headline parallel run and under the
    ``mixedbin_hybrid_`` prefix the composed satellite lane copies).
    ``mixedbin_expected`` guards ``auto``: a genuinely single-class
    table resolving off is a correct resolution, not a regression."""
    rec = latest["rec"]
    for prefix in ("", "mixedbin_hybrid_"):
        learner = rec.get(prefix + "tree_learner")
        requested = rec.get(prefix + "mixed_bin_requested")
        resolved = rec.get(prefix + "mixed_bin_on")
        expected = rec.get(prefix + "mixedbin_expected")
        if learner not in ("hybrid", "voting") or resolved is not False:
            continue
        if requested == "true" or (requested == "auto" and expected):
            findings.append({
                "metric": metric,
                "key": (prefix or "headline_") + "mixed_bin_resolution",
                "latest_round": latest["round"],
                "latest": False, "baseline": True,
                "detail": "%s round requested mixed_bin=%s on a "
                          "mixed-cardinality table but resolved the "
                          "uniform layout (block-local packing silently "
                          "fell back)" % (learner, requested),
            })


def _check_drift_slo(metric: str, latest: dict,
                     findings: List[dict]) -> None:
    """ISSUE 20 absolute findings on the latest bench round, no
    trajectory needed: ``drift_aa_psi`` above the documented A/A bound
    means the score-drift detector's false-positive floor rose past its
    own spec (every production swap would risk a spurious drift page),
    and ``monitor_slo_breaches > 0`` on a round that did NOT declare an
    induced fault means the generous bench SLO (20x the measured
    healthy p99) burned on healthy load — either the serving path
    developed a real bulge or the burn arithmetic broke."""
    rec = latest["rec"]
    aa = rec.get("drift_aa_psi")
    if isinstance(aa, (int, float)) and aa > AA_PSI_BOUND:
        findings.append({
            "metric": metric, "key": "drift_aa_psi",
            "latest_round": latest["round"],
            "latest": aa, "baseline": AA_PSI_BOUND,
            "detail": "A/A self-check PSI %.4g exceeds the documented "
                      "false-positive bound %.2g — same-distribution "
                      "halves look drifted, so every real drift verdict "
                      "is suspect" % (aa, AA_PSI_BOUND),
        })
    breaches = rec.get("monitor_slo_breaches")
    if isinstance(breaches, (int, float)) and breaches > 0 \
            and not rec.get("monitor_induced_fault"):
        findings.append({
            "metric": metric, "key": "monitor_slo_breaches",
            "latest_round": latest["round"],
            "latest": breaches, "baseline": 0,
            "detail": "SLO burn-rate breach(es) fired on a healthy "
                      "bench round with no declared induced fault — the "
                      "20x-generous objective burned on steady load",
        })


def _check_ingest_workers(metric: str, entries: List[dict],
                          findings: List[dict]) -> None:
    """ISSUE 18: the parallel-ingest lanes.  Two contracts, checked on
    EVERY round that recorded ``ingest_workers > 1`` (like the
    mixed-bin resolution check, these are claims about that round, not
    trajectories):

    - must-GROW: a round that ran the byte-range worker pool exists to
      beat the serial tokenizer — its ``ingest_rows_per_sec`` must
      strictly exceed the serial baseline.  The baseline is the
      round's OWN recorded ``ingest_serial_rows_per_sec`` when present
      (the bench lane prices both loaders on the same file, same scale,
      same host — the matched comparison), else the median of all
      strictly-earlier rounds that did NOT record
      ``ingest_workers > 1`` (the r06-r08 serial history).  A parallel
      round at-or-below serial throughput means the fan-out stopped
      paying and must not pass unnoticed.  Skipped when neither
      baseline exists.
    - absolute: a round that REQUESTED workers but recorded
      ``ingest_workers_effective <= 1`` silently resolved to the serial
      loader (fork unavailable, or the dispatch fell through) — the
      lane would then gate serial numbers as if they were parallel."""
    for i, e in enumerate(entries):
        rec = e["rec"]
        workers = rec.get("ingest_workers")
        if not isinstance(workers, (int, float)) or workers <= 1:
            continue
        effective = rec.get("ingest_workers_effective")
        if isinstance(effective, (int, float)) and effective <= 1:
            findings.append({
                "metric": metric, "key": "ingest_workers_effective",
                "latest_round": e["round"],
                "latest": effective, "baseline": workers,
                "detail": "round requested ingest_workers=%d but the "
                          "load resolved to the serial parse silently "
                          "(effective=%d)" % (workers, effective),
            })
        rate = rec.get("ingest_rows_per_sec")
        if not isinstance(rate, (int, float)):
            continue
        own_serial = rec.get("ingest_serial_rows_per_sec")
        if isinstance(own_serial, (int, float)):
            baseline = float(own_serial)
        else:
            serial_prior = [
                float(p["rec"]["ingest_rows_per_sec"])
                for p in entries[:i]
                if isinstance(p["rec"].get("ingest_rows_per_sec"),
                              (int, float))
                and not (isinstance(p["rec"].get("ingest_workers"),
                                    (int, float))
                         and p["rec"]["ingest_workers"] > 1)]
            if not serial_prior:
                continue
            baseline = _median(serial_prior)
        if baseline > 0 and float(rate) <= baseline:
            findings.append({
                "metric": metric, "key": "ingest_rows_per_sec_must_grow",
                "latest_round": e["round"],
                "latest": float(rate), "baseline": round(baseline, 6),
                "detail": "round ran ingest_workers=%d but "
                          "ingest_rows_per_sec did not grow past the "
                          "serial baseline (%s) — the parallel parse "
                          "stopped paying"
                          % (workers,
                             "same-record serial lane"
                             if isinstance(own_serial, (int, float))
                             else "serial-round median"),
            })


def _multichip_obs_value(rec: dict, key: str) -> Optional[float]:
    """The two gated observability series on a multichip record."""
    if key == "skew/max_phase_skew":
        skew = rec.get("skew")
        if isinstance(skew, dict) and isinstance(
                skew.get("max_phase_skew"), (int, float)):
            # a round that compared no iterations has no skew signal
            if skew.get("iterations_compared", 0) > 0 \
                    and skew["max_phase_skew"] > 0:
                return float(skew["max_phase_skew"])
        return None
    if key == "interconnect/attained_gb_per_s":
        ic = rec.get("interconnect")
        if isinstance(ic, dict) and isinstance(
                ic.get("attained_gb_per_s"), (int, float)) \
                and ic["attained_gb_per_s"] > 0:
            return float(ic["attained_gb_per_s"])
    if key.startswith("wire/"):
        wire = rec.get("wire")
        if isinstance(wire, dict):
            v = (wire.get("wire_bytes_per_iter") or {}).get(
                key.split("/", 1)[1])
            if isinstance(v, (int, float)) and v > 0:
                return float(v)
    return None


def _check_multichip(entries: List[dict], findings: List[dict],
                     floor: float = DEFAULT_FLOOR,
                     sigma_mult: float = DEFAULT_SIGMA_MULT) -> None:
    entries = sorted(entries, key=lambda e: e["round"])
    # ISSUE 14 absolute contracts on the kill-restart row, checked on
    # every round that recorded one: a restore that lost finished trees
    # or metric records, or that diverged from the uninterrupted run's
    # budget class, must not pass the gate
    for e in entries:
        el = e["rec"].get("elastic")
        if not isinstance(el, dict):
            continue
        for akey, detail in (
                ("restore_match",
                 "the restarted run's final model diverged from the "
                 "uninterrupted reference beyond the documented budget "
                 "class"),
                ("metrics_complete",
                 "iteration/metric records were lost across the "
                 "kill-restart (coverage of the iteration range has "
                 "gaps)")):
            if el.get(akey) is False:
                findings.append({
                    "metric": "multichip", "key": "elastic/" + akey,
                    "latest_round": e["round"],
                    "latest": False, "baseline": True,
                    "detail": detail,
                })
    if len(entries) < 2:
        return
    latest = entries[-1]
    if not latest["rec"].get("ok", False) and any(
            e["rec"].get("ok") for e in entries[:-1]):
        findings.append({
            "metric": "multichip", "key": "ok",
            "latest_round": latest["round"],
            "latest": False, "baseline": True,
            "detail": "multichip smoke went ok -> not-ok",
        })
    # ISSUE 5: the skew/interconnect trajectory.  No recorded spread for
    # these series, and the smoke runs are tiny (compile warmth and host
    # load dominate — the simulated-host skew legitimately swings ~2x),
    # so the band floor is wide: these series catch ORDER-OF-MAGNITUDE
    # breaks (a collective route regression, a new persistent straggler),
    # not percent drift.  sigma = band/2 like the rate keys.
    sigma = max(floor, _OBS_FLOOR) / 2.0
    for key, direction in (("skew/max_phase_skew", "up"),
                           ("interconnect/attained_gb_per_s", "down")):
        series = [(e["round"], _multichip_obs_value(e["rec"], key))
                  for e in entries]
        series = [(r, v) for r, v in series if v is not None]
        if len(series) < 2 or series[-1][0] != latest["round"]:
            continue
        prior = [v for _, v in series[:-1]]
        latest_v = series[-1][1]
        baseline = _median(prior)
        if baseline <= 0:
            continue
        if direction == "up":
            threshold = baseline * (1.0 + sigma_mult * sigma)
            regressed = latest_v > threshold
            drop = latest_v / baseline - 1.0
        else:
            threshold = baseline * (1.0 - sigma_mult * sigma)
            regressed = latest_v < threshold
            drop = 1.0 - latest_v / baseline
        if regressed:
            findings.append({
                "metric": "multichip", "key": key,
                "latest_round": latest["round"],
                "latest": latest_v, "baseline": round(baseline, 6),
                "drop": round(drop, 4),
                "allowed_drop": round(sigma_mult * sigma, 4),
            })


def _check_podtrace(entries: List[dict], findings: List[dict],
                    floor: float = DEFAULT_FLOOR,
                    sigma_mult: float = DEFAULT_SIGMA_MULT) -> None:
    """ISSUE 17: the pod-merge bookkeeping from the MULTICHIP_PODTRACE
    block.  Absolute contracts checked on EVERY round that recorded one
    (these are correctness claims about that round's merge, not
    trajectories): alignment error exceeding the dump's own recorded
    collective-duration bound, any real pod_report --check finding, a
    measured seam missing from the byte model, and live-vs-post-mortem
    straggler verdict divergence.  The normalized merge overhead
    (``merge_ms_per_kevent``) rides a must-not-grow lane at the wide
    observability floor — the smoke merges a tiny ring, so only
    order-of-magnitude breaks (an accidentally quadratic merge) are
    signal."""
    entries = sorted(entries, key=lambda e: e["round"])
    for e in entries:
        pt = e["rec"].get("podtrace")
        if not isinstance(pt, dict):
            continue
        checks = (
            ("alignment_ok", pt.get("alignment_ok") is False,
             "a host's clock-offset estimates disagree beyond the "
             "recorded collective-duration bounds — the alignment error "
             "exceeded the bound the dumps themselves recorded"),
            ("check_findings",
             isinstance(pt.get("check_findings"), (int, float))
             and pt["check_findings"] > 0,
             "pod_report --check flagged merge-contract violations "
             "(header bookkeeping / event conservation / attribution "
             "identity)"),
            ("unmodeled",
             isinstance(pt.get("unmodeled"), (int, float))
             and pt["unmodeled"] > 0,
             "measured collective seam(s) missing from the wire byte "
             "model — byte-model drift"),
            ("parity", pt.get("parity") is False,
             "the post-mortem straggler verdict diverged from the live "
             "StragglerTracker's over the same measurements — the one-"
             "rule contract is broken"),
        )
        for key, bad, detail in checks:
            if bad:
                findings.append({
                    "metric": "multichip", "key": "podtrace/" + key,
                    "latest_round": e["round"],
                    "latest": pt.get(key), "baseline": None,
                    "detail": detail,
                })
    series = [(e["round"], float(pt["merge_ms_per_kevent"]))
              for e in entries
              for pt in [e["rec"].get("podtrace")]
              if isinstance(pt, dict) and isinstance(
                  pt.get("merge_ms_per_kevent"), (int, float))
              and pt["merge_ms_per_kevent"] > 0]
    if len(series) < 2 or series[-1][0] != entries[-1]["round"]:
        return
    prior = [v for _, v in series[:-1]]
    latest_v = series[-1][1]
    baseline = _median(prior)
    sigma = max(floor, _OBS_FLOOR) / 2.0
    if baseline > 0 and latest_v > baseline * (1.0 + sigma_mult * sigma):
        findings.append({
            "metric": "multichip", "key": "podtrace/merge_ms_per_kevent",
            "latest_round": series[-1][0],
            "latest": latest_v, "baseline": round(baseline, 6),
            "drop": round(latest_v / baseline - 1.0, 4),
            "allowed_drop": round(sigma_mult * sigma, 4),
        })


def _check_sharded_ingest(entries: List[dict],
                          findings: List[dict]) -> None:
    """ISSUE 18c: the multi-host sharded-ingest row from the
    MULTICHIP_SHARDED_INGEST block.  Absolute per-round contracts (no
    trajectory): every rank parses only its own row shard's byte
    ranges, so the per-host parsed-row counts must sum to the dataset
    with zero overlap, tile it exactly (coverage), and bin
    bit-identically to the serial masked load."""
    for e in sorted(entries, key=lambda e: e["round"]):
        si = e["rec"].get("sharded_ingest")
        if not isinstance(si, dict):
            continue
        host_rows = si.get("host_rows")
        total = si.get("total")
        rows_sum = (sum(host_rows) if isinstance(host_rows, list)
                    and all(isinstance(v, (int, float))
                            for v in host_rows) else None)
        checks = (
            ("ok", si.get("ok") is False, si.get("ok"),
             "the sharded-ingest smoke failed outright"),
            ("host_rows_sum",
             rows_sum is not None and isinstance(total, (int, float))
             and rows_sum != total, rows_sum,
             "per-host parsed-row counts do not sum to the dataset "
             "(%s != %s)" % (rows_sum, total)),
            ("overlap",
             isinstance(si.get("overlap"), (int, float))
             and si["overlap"] > 0, si.get("overlap"),
             "hosts parsed overlapping global rows — shard ownership "
             "leaked across ranks"),
            ("coverage_ok", si.get("coverage_ok") is False,
             si.get("coverage_ok"),
             "the union of per-host row shards does not tile the "
             "dataset exactly"),
            ("bit_identical", si.get("bit_identical") is False,
             si.get("bit_identical"),
             "a host's sharded parse binned differently from the "
             "serial masked load"),
        )
        for key, bad, latest, detail in checks:
            if bad:
                findings.append({
                    "metric": "multichip",
                    "key": "sharded_ingest/" + key,
                    "latest_round": e["round"],
                    "latest": latest, "baseline": None,
                    "detail": detail,
                })


def _check_monitor(entries: List[dict], findings: List[dict]) -> None:
    """ISSUE 20: the live-monitor row from the MULTICHIP_MONITOR block.
    Absolute per-round contracts (correctness claims about that round's
    smoke, not trajectories): the induced latency bulge must trip the
    fast+slow burn rule, the shifted-score swap must trip the PSI drift
    verdict, the healthy engine's A/A self-check must hold under its
    bound, and both the monitor_report and trace_report checkers must
    come back clean (delta/total conservation, burn arithmetic,
    re-derived drift verdicts, slo_breach <-> monitor_window linkage)."""
    for e in sorted(entries, key=lambda e: e["round"]):
        mon = e["rec"].get("monitor")
        if not isinstance(mon, dict):
            continue
        checks = (
            ("breaches",
             isinstance(mon.get("breaches"), (int, float))
             and mon["breaches"] < 1, mon.get("breaches"),
             "the induced latency bulge did not trip the fast+slow SLO "
             "burn rule — the monitor missed the exact failure it "
             "exists for"),
            ("drift", mon.get("drift") is False, mon.get("drift"),
             "the shifted-score engine swap did not trip the PSI drift "
             "verdict"),
            ("aa_ok", mon.get("aa_ok") is False, mon.get("aa_psi"),
             "the healthy engine's A/A self-check exceeded its "
             "false-positive bound"),
            ("check_findings",
             isinstance(mon.get("check_findings"), (int, float))
             and mon["check_findings"] > 0, mon.get("check_findings"),
             "monitor_report --check flagged contract violations "
             "(delta/total conservation, burn arithmetic, or a drift "
             "verdict disagreeing with its own buckets)"),
            ("trace_check_findings",
             isinstance(mon.get("trace_check_findings"), (int, float))
             and mon["trace_check_findings"] > 0,
             mon.get("trace_check_findings"),
             "trace_report --check flagged the monitored round's dump "
             "(slo_breach <-> monitor_window linkage or ring "
             "contracts)"),
        )
        for key, bad, latest, detail in checks:
            if bad:
                findings.append({
                    "metric": "multichip", "key": "monitor/" + key,
                    "latest_round": e["round"],
                    "latest": latest, "baseline": None,
                    "detail": detail,
                })


def _check_wire(entries: List[dict], findings: List[dict],
                floor: float = DEFAULT_FLOOR,
                sigma_mult: float = DEFAULT_SIGMA_MULT) -> None:
    """ISSUE 9: the logical wire-bytes-per-iteration series from the
    MULTICHIP_WIRE block.  Two absolute findings on the latest round
    (hybrid >= pure-DP bytes; voting >= hybrid bytes — the 2-D/voted
    restrictions stopped paying), plus a must-not-grow gate per learner
    with the TIGHT rate-key band (the series is deterministic: traced
    shapes x loop estimates, zero timing noise), compared only across
    rounds at the same device count."""
    latest = entries[-1]
    wire = latest["rec"].get("wire")
    if isinstance(wire, dict):
        w = wire.get("wire_bytes_per_iter") or {}
        for a, b in (("hybrid", "data"), ("voting", "hybrid")):
            va, vb = w.get(a), w.get(b)
            if isinstance(va, (int, float)) and isinstance(
                    vb, (int, float)) and va >= vb > 0:
                findings.append({
                    "metric": "multichip", "key": "wire/%s_vs_%s" % (a, b),
                    "latest_round": latest["round"],
                    "latest": va, "baseline": vb,
                    "detail": "%s records >= %s logical wire bytes per "
                              "iteration on the same device count" % (a, b),
                })
    if len(entries) < 2:
        return
    sigma = floor / 2.0
    nd = (wire or {}).get("n_devices")
    for learner in ("data", "hybrid", "voting"):
        key = "wire/" + learner
        series = [(e["round"], _multichip_obs_value(e["rec"], key))
                  for e in entries
                  if (e["rec"].get("wire") or {}).get("n_devices") == nd]
        series = [(r, v) for r, v in series if v is not None]
        if len(series) < 2 or series[-1][0] != latest["round"]:
            continue
        prior = [v for _, v in series[:-1]]
        latest_v = series[-1][1]
        baseline = _median(prior)
        if baseline <= 0:
            continue
        if latest_v > baseline * (1.0 + sigma_mult * sigma):
            findings.append({
                "metric": "multichip", "key": key,
                "latest_round": latest["round"],
                "latest": latest_v, "baseline": round(baseline, 6),
                "drop": round(latest_v / baseline - 1.0, 4),
                "allowed_drop": round(sigma_mult * sigma, 4),
            })


def check_files(paths: List[str], floor: float = DEFAULT_FLOOR,
                sigma_mult: float = DEFAULT_SIGMA_MULT,
                allow_cross_hardware: bool = False) -> dict:
    """Gate a trajectory; returns the report dict (``findings`` empty on
    a clean pass).  Raises GateError on malformed/uncomparable input."""
    if not paths:
        raise GateError("no bench history files matched")
    entries = [load_entry(p) for p in paths]
    groups: Dict[str, List[dict]] = {}
    multichip: List[dict] = []
    for e in entries:
        if e["kind"] == "multichip":
            multichip.append(e)
        else:
            groups.setdefault(str(e["rec"].get("metric", "?")),
                              []).append(e)
    findings: List[dict] = []
    for metric, group in sorted(groups.items()):
        _check_group(metric, group, floor, sigma_mult,
                     allow_cross_hardware, findings)
    _check_multichip(multichip, findings, floor=floor,
                     sigma_mult=sigma_mult)
    if multichip:
        _check_wire(sorted(multichip, key=lambda e: e["round"]), findings,
                    floor=floor, sigma_mult=sigma_mult)
        _check_podtrace(multichip, findings, floor=floor,
                        sigma_mult=sigma_mult)
        _check_sharded_ingest(multichip, findings)
        _check_monitor(multichip, findings)
    return {
        "files": len(entries),
        "groups": {m: len(g) for m, g in sorted(groups.items())},
        "multichip_rounds": len(multichip),
        "sigma_mult": sigma_mult, "floor": floor,
        "findings": findings,
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--check", nargs="+", metavar="GLOB", required=True,
                   help="bench history globs, e.g. 'BENCH_r*.json' "
                        "'MULTICHIP_r*.json'")
    p.add_argument("--floor", type=float, default=DEFAULT_FLOOR,
                   help="minimum relative noise band when no spread is "
                        "recorded (default %(default)s)")
    p.add_argument("--sigma-mult", type=float, default=DEFAULT_SIGMA_MULT,
                   help="flag drops beyond this many sigmas "
                        "(sigma = band/2; default %(default)s)")
    p.add_argument("--allow-cross-hardware", action="store_true",
                   help="compare rounds across device kinds anyway")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    args = p.parse_args(argv)
    paths = sorted({f for g in args.check for f in glob.glob(g)})
    try:
        report = check_files(paths, floor=args.floor,
                             sigma_mult=args.sigma_mult,
                             allow_cross_hardware=args.allow_cross_hardware)
    except GateError as e:
        print(f"perf_gate error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    else:
        for f in report["findings"]:
            if "drop" in f:
                print("REGRESSION %s %s: round %s at %.4g, %.1f%% below "
                      "the prior median %.4g (allowed %.1f%%)"
                      % (f["metric"], f["key"], f["latest_round"],
                         f["latest"], 100 * f["drop"], f["baseline"],
                         100 * f["allowed_drop"]))
            else:
                print("REGRESSION %s %s: %s"
                      % (f["metric"], f["key"],
                         f.get("detail", "regressed")))
        if not report["findings"]:
            print("perf_gate: %d file(s), %d metric group(s) — no "
                  "regression beyond the noise bands"
                  % (report["files"], len(report["groups"])))
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
