"""Honest op timing over the axon tunnel.

Dispatch-only timing lies (async), and per-call readback pays ~100ms RPC.
This harness chains R executions of an op inside ONE jitted fori_loop (each
iteration's input is perturbed by the carry so XLA cannot hoist the body),
reads back one scalar, and reports (T(R2) - T(R1)) / (R2 - R1).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def device_time(op, *args, reps=(2, 10), key_arg=0, readback=True):
    """Seconds per execution of op(*args), measured on-device.

    key_arg: index of a float array argument to perturb with the carry
    (keeps the loop body live across iterations).
    """

    def run(reps):
        @jax.jit
        def prog(eps, *a):
            def body(_, carry):
                a2 = list(a)
                a2[key_arg] = a2[key_arg] + (eps * carry).astype(
                    a2[key_arg].dtype)
                out = op(*a2)
                leaves = jax.tree_util.tree_leaves(out)
                s = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
                return carry + s * eps
            return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))

        out = prog(jnp.float32(0.0), *args)   # compile+warm
        _ = np.asarray(out)
        t0 = time.perf_counter()
        out = prog(jnp.float32(0.0), *args)
        _ = np.asarray(out)
        return time.perf_counter() - t0

    r1, r2 = reps
    t1 = run(r1)
    t2 = run(r2)
    return (t2 - t1) / (r2 - r1)


if __name__ == "__main__":
    a = jnp.ones((8192, 8192), jnp.bfloat16)
    b = jnp.ones((8192, 8192), jnp.bfloat16)
    t = device_time(lambda x, y: jnp.dot(x, y, preferred_element_type=jnp.float32),
                    a, b)
    print(f"8192^3 bf16 matmul: {t*1e3:.3f} ms -> {2*8192**3/t/1e12:.0f} TFLOP/s")
