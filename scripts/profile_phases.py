"""Per-phase cost attribution for the depthwise training iteration.

Two methodologies:

``--mode=stub`` (the original): bench-style A/B at full scale — the only
low-noise end-to-end ground truth on the tunneled TPU.  Times the SAME
fused k-iteration chunk program in variants that stub one phase each, so
the phase cost falls out as a difference of end-to-end rates:

  full        : unmodified train_chunk
  nohist      : histogram_leafbatch replaced by a cheap data-dependent
                broadcast (keeps the program structure and all downstream
                consumers; removes the MXU one-hot passes)

``--mode=telemetry``: reads the telemetry subsystem's phase spans
(lightgbm_tpu/telemetry.py) instead of stubbing.  The fused program is
host-indivisible, so the span read runs ONE iteration eagerly
(jax.disable_jit + fence mode — every op executes and blocks as its own
dispatch) to attribute wall time to histogram / split_find / partition,
then scales those FRACTIONS onto the separately-measured jitted
sec/iter.  Eager dispatch overhead inflates the non-histogram tail, so
treat the stub difference as ground truth for absolutes and the span
fractions as the per-phase decomposition; ``--cross-check`` runs the
nohist stub variant too and prints both attributions side by side.

Usage: python scripts/profile_phases.py --rows 11000000 --iters 8
       python scripts/profile_phases.py --mode=telemetry --rows 200000
Prints one JSON line per variant.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_variant(variant: str, args) -> float:
    import jax
    import jax.numpy as jnp
    import lightgbm_tpu  # noqa: F401
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log
    from lightgbm_tpu.models import grower_depthwise
    from lightgbm_tpu.ops import histogram

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)

    if variant == "nohist":
        real = histogram.histogram_leafbatch

        def stub(bins, grad, hess, col_id, col_ok, num_cols, num_bins_max,
                 chunk=65536, compute_dtype=jnp.bfloat16, axis_name=None):
            F = bins.shape[0]
            # data-dependent (not constant-foldable), trivially cheap
            seed = (jnp.sum(grad[:8]) + col_id[0].astype(jnp.float32))
            return jnp.full((num_cols, F, num_bins_max, 3), 1.0,
                            jnp.float32) * (1.0 + 1e-12 * seed)

        grower_depthwise.histogram_leafbatch = stub

    from bench import make_data

    x, y = make_data(args.rows, args.features)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)

    cfg = OverallConfig()
    cfg.set({
        "objective": "binary", "num_leaves": str(args.leaves),
        "min_data_in_leaf": "100", "min_sum_hessian_in_leaf": "10.0",
        "learning_rate": "0.1", "grow_policy": "depthwise",
        "hist_dtype": args.hist_dtype,
        "num_iterations": str(2 * args.iters),
    }, require_data=False)

    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))
    booster.train_chunk(args.iters)
    jax.block_until_ready(booster.score)
    # perf_counter: monotonic (an NTP step would corrupt the rate)
    start = time.perf_counter()
    booster.train_chunk(args.iters)
    jax.block_until_ready(booster.score)
    elapsed = time.perf_counter() - start
    if variant == "nohist":
        grower_depthwise.histogram_leafbatch = real
    return args.iters / elapsed


def run_telemetry(args) -> dict:
    """Span-based attribution: jitted rate for the absolute sec/iter, one
    eager fenced iteration for the per-phase decomposition."""
    import jax
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.config import OverallConfig
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log
    from bench import make_data

    log.set_stream(sys.stderr)
    log.set_level(log.WARNING)

    x, y = make_data(args.rows, args.features)
    ds = Dataset.from_arrays(x, y, max_bin=args.max_bin)
    cfg = OverallConfig()
    cfg.set({
        "objective": "binary", "num_leaves": str(args.leaves),
        "min_data_in_leaf": "100", "min_sum_hessian_in_leaf": "10.0",
        "learning_rate": "0.1", "grow_policy": "depthwise",
        "hist_dtype": args.hist_dtype,
        "num_iterations": str(2 * args.iters),
    }, require_data=False)
    booster = GBDT()
    booster.init(cfg.boosting_config, ds,
                 create_objective(cfg.objective_type, cfg.objective_config))

    # jitted end-to-end rate (the absolute scale the fractions map onto).
    # Telemetry armed for the jitted pass too (ISSUE 4): the cost registry
    # captures the chunk program's cost_analysis + compile seconds, and the
    # measured train_chunk span joins them into a roofline block
    telemetry.enable()
    telemetry.reset()
    booster.train_chunk(args.iters)
    jax.block_until_ready(booster.score)
    start = time.perf_counter()
    booster.train_chunk(args.iters)
    jax.block_until_ready(booster.score)
    sec_per_iter = (time.perf_counter() - start) / args.iters
    jit_snap = telemetry.snapshot()

    # one eager fenced iteration: every op span measures real execution
    # (reset clears the jitted pass's spans — the roofline block above is
    # already captured in jit_snap)
    telemetry.enable(fence=True)
    telemetry.reset()
    t0 = time.perf_counter()
    with jax.disable_jit():
        booster.train_one_iter(is_eval=False)
    eager_sec = time.perf_counter() - t0
    snap = telemetry.snapshot()
    telemetry.disable()

    pt = snap["phase_times"]
    phases = {k: pt.get(k, 0.0)
              for k in ("histogram", "split_find", "partition")}
    fractions = {k: round(v / eager_sec, 4) for k, v in phases.items()}
    out = {
        "mode": "telemetry", "rows": args.rows,
        "hist_dtype": args.hist_dtype,
        "iters_per_sec": round(1.0 / sec_per_iter, 4),
        "sec_per_iter": round(sec_per_iter, 4),
        "eager_sec": round(eager_sec, 4),
        "phase_times_eager": {k: round(v, 4) for k, v in pt.items()},
        "phase_fractions": fractions,
        "est_sec_per_iter": {k: round(f * sec_per_iter, 4)
                             for k, f in fractions.items()},
        "counters": dict(sorted(snap["counters"].items())),
    }
    # roofline/compile from the JITTED pass (ISSUE 4): attained rates over
    # the fused program's measured wall time, the compiled-program
    # inventory, and the analytic per-pass MAC notes
    if "roofline" in jit_snap:
        out["roofline"] = jit_snap["roofline"]
    if "compile" in jit_snap:
        out["compile"] = jit_snap["compile"]
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=11_000_000)
    p.add_argument("--features", type=int, default=28)
    p.add_argument("--leaves", type=int, default=255)
    p.add_argument("--max-bin", type=int, default=255)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--mode", default="stub", choices=["stub", "telemetry"])
    p.add_argument("--variant", default="full",
                   choices=["full", "nohist"])
    p.add_argument("--cross-check", action="store_true",
                   help="telemetry mode: also run the nohist stub variant "
                        "(subprocess) and report both histogram "
                        "attributions side by side")
    p.add_argument("--hist-dtype", default="float32",
                   choices=["float32", "bfloat16", "int8"])
    args = p.parse_args()
    if args.mode == "telemetry":
        out = run_telemetry(args)
        if args.cross_check and args.hist_dtype != "int8":
            import subprocess
            full = out["sec_per_iter"]
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--mode", "stub", "--variant", "nohist",
                   "--rows", str(args.rows), "--features",
                   str(args.features), "--leaves", str(args.leaves),
                   "--max-bin", str(args.max_bin), "--iters",
                   str(args.iters), "--hist-dtype", args.hist_dtype]
            try:
                res = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=3600, check=True)
                sub = json.loads(res.stdout.strip().splitlines()[-1])
                stub_hist = full - sub["sec_per_iter"]
                out["cross_check"] = {
                    "stub_hist_sec_per_iter": round(stub_hist, 4),
                    "telemetry_hist_sec_per_iter":
                        out["est_sec_per_iter"]["histogram"],
                }
            except Exception as e:
                out["cross_check_error"] = f"{type(e).__name__}: {e}"[:400]
        print(json.dumps(out))
        return
    if args.variant == "nohist" and args.hist_dtype == "int8":
        # int8 derives root stats FROM the histogram (grower_depthwise);
        # a stubbed histogram would grow a structurally different tree and
        # the full-minus-nohist subtraction would compare two different
        # programs
        raise SystemExit("--variant nohist requires a float hist dtype "
                         "(int8 root stats are histogram-derived)")
    rate = run_variant(args.variant, args)
    print(json.dumps({"variant": args.variant, "mode": "stub",
                      "rows": args.rows,
                      "hist_dtype": args.hist_dtype,
                      "iters_per_sec": round(rate, 4),
                      "sec_per_iter": round(1.0 / rate, 4)}))


if __name__ == "__main__":
    main()
