"""Partition-window DMA-overlap A/B — the PROFILE.md "pending" number.

PR 3 made the overlapped window-DMA schedule the partition kernel's
default (ops/compact._partition_kernel_overlap) with
``LGBM_TPU_PARTITION_NO_OVERLAP=1`` as the serialized A/B hatch, but the
TPU measurement was never recorded.  This script runs that A/B through
scripts/tpu_timeit's carry-perturbed fori harness (honest on-device
seconds, no dispatch-only lies) at the bench pane shape.

On a backend where the Pallas kernel is ineligible (CPU CI included) the
overlap bit is a no-op — partition routes to the XLA oracle — so the
script reports the oracle timing and says exactly that, instead of
printing a fake A/B.

Usage: python scripts/partition_ab.py [--rows N] [--features F]
Prints one JSON line.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=1_000_000,
                   help="segment lanes (bench scale: 1M)")
    p.add_argument("--features", type=int, default=28)
    p.add_argument("--left-frac", type=float, default=0.5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import compact
    from tpu_timeit import device_time

    backend = jax.default_backend()
    eligible = backend == "tpu" and compact.pallas_partition_ok(args.features)
    R = compact.pane_rows(args.features)
    W = ((args.rows + compact.BLOCK - 1) // compact.BLOCK) * compact.BLOCK
    rng = np.random.RandomState(0)
    seg = jnp.asarray(rng.randint(-128, 128, (R, W)), jnp.int8)
    cnt = args.rows
    go_left = rng.rand(W) < args.left_frac
    mask3 = np.where(np.arange(W) < cnt,
                     go_left.astype(np.int8), np.int8(-1))
    plcnt = int(mask3[:cnt].sum())
    mask3 = jnp.asarray(mask3)
    delta = jnp.int32(0)

    def run(use_pallas: bool, overlap: bool) -> float:
        return device_time(
            lambda s, m: compact._partition_segment_impl(
                s, m, delta, jnp.int32(cnt), jnp.int32(plcnt),
                block=compact.BLOCK, use_pallas=use_pallas,
                interpret=False, overlap=overlap),
            seg, mask3)

    out = {
        "backend": backend,
        "device_kind": str(jax.local_devices()[0].device_kind),
        "pallas_eligible": bool(eligible),
        "rows": args.rows, "features": args.features,
        "pane_shape": [int(R), int(W)],
    }
    if eligible:
        on = run(True, True)
        off = run(True, False)
        out["overlap_on_ms"] = round(on * 1e3, 3)
        out["overlap_off_ms"] = round(off * 1e3, 3)
        out["overlap_speedup"] = round(off / on, 4) if on > 0 else None
    else:
        out["xla_oracle_ms"] = round(run(False, True) * 1e3, 3)
        out["note"] = (
            "Pallas partition ineligible on backend=%s — partition routes "
            "to the XLA oracle, where the DMA-overlap flag is a no-op; "
            "the overlap A/B needs a TPU round" % backend)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
