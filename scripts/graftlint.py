#!/usr/bin/env python
"""graftlint — static AST + jaxpr + concurrency/drift analyzer (gate).

Runs beside ``scripts/perf_gate.py --check`` with the same exit-code
contract (0 clean / 1 findings / 2 tool error):

    python scripts/graftlint.py --check

Layer 1 (AST, no JAX needed) walks the package source for the
review-hardening rule catalog (R1 collective-seam-coverage, R2
cache-key-completeness, R3 span-fencing, R4
banned-patterns-in-traced-code); Layer 2 traces the canonical
small-schema programs (serial/DP/hybrid/voting grow, serving BFS, the
int8 histogram exchange) under ``JAX_PLATFORMS=cpu`` and walks their
closed jaxprs (J1 dtype discipline, J2 collective census vs the declared
telemetry seam inventory).  Layer 3 (ISSUE 15, no JAX needed) covers
the threaded subsystems (C1 thread-lifecycle-registration, C2
future-set-race, C3 blocking-under-lock, C4 env-hatch-discipline) and
the cross-artifact drift censuses (D1 telemetry name families, D2
perf_gate key coverage, D3 the CLI knob inventory).  Findings print
``path:line RULE [symbol] site: message — fix: hint``.

Accepted sites are suppressed EXPLICITLY in ``GRAFTLINT_BASELINE.json``
(each entry carries a written justification; ``--explain-allowlist``
prints them).  A baseline entry that matches nothing is reported as
stale — the baseline can only shrink or be consciously re-justified.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# Layer 2 traces shard_map programs over a simulated multi-device mesh;
# both knobs must land before jax initializes its backend (same dance as
# tests/conftest.py — the environment's sitecustomize may import jax
# first, so jax.config.update below is the authoritative one).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--check", action="store_true",
                   help="run every layer against the baseline (the "
                        "pre-merge gate; this is also the default)")
    p.add_argument("--ast-only", action="store_true",
                   help="layer 1 only (no JAX import — runs anywhere)")
    p.add_argument("--jaxpr-only", action="store_true",
                   help="layer 2 only (traces the canonical programs)")
    p.add_argument("--concurrency-only", action="store_true",
                   help="layer 3 C-rules only (thread/Future lifecycle "
                        "+ env-hatch discipline; no JAX import)")
    p.add_argument("--drift-only", action="store_true",
                   help="layer 3 D-rules only (telemetry/perf_gate/knob "
                        "cross-artifact censuses; no JAX import)")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help="baseline/allowlist file (default: "
                        "GRAFTLINT_BASELINE.json at the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, ignoring every suppression")
    p.add_argument("--explain-allowlist", action="store_true",
                   help="print every baseline entry with its written "
                        "justification, then exit 0")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    args = p.parse_args(argv)

    from lightgbm_tpu.analysis import driver
    from lightgbm_tpu.analysis.findings import Baseline

    baseline_path = args.baseline or driver.default_baseline_path()
    baseline = None
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print("graftlint error: bad baseline %s: %s"
                  % (baseline_path, e), file=sys.stderr)
            return 2

    if args.explain_allowlist:
        entries = baseline.entries if baseline else []
        if not entries:
            print("graftlint: baseline is empty — no allowlisted sites")
        for e in entries:
            print("%s %s [%s] %s\n    justification: %s"
                  % (e["rule"], e["path"], e["symbol"],
                     e.get("site", "*"), e["justification"]))
        return 0

    selected = [layer for layer, on in (
        ("ast", args.ast_only), ("jaxpr", args.jaxpr_only),
        ("concurrency", args.concurrency_only),
        ("drift", args.drift_only)) if on]
    layers = tuple(selected) or driver.ALL_LAYERS

    try:
        report = driver.run(layers=layers, baseline=baseline)
    except driver.GraftlintError as e:
        print("graftlint error: %s" % e, file=sys.stderr)
        return 2

    findings = report["findings"]
    stale = report["stale_baseline"]
    if args.json:
        print(json.dumps({
            "findings": [f._asdict() for f in findings],
            "suppressed": [f._asdict() for f in report["suppressed"]],
            "stale_baseline": stale,
        }))
    else:
        for f in findings:
            print(f.format())
        for e in stale:
            print("STALE BASELINE %s %s [%s]: matched nothing — remove "
                  "or re-justify" % (e["rule"], e["path"], e["symbol"]))
        if not findings and not stale:
            print("graftlint: %s layer(s) clean (%d suppression(s) "
                  "applied)" % ("+".join(layers),
                                len(report["suppressed"])))
    return 1 if (findings or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
